"""Speculative decoding on the paged KV pool + the Pallas block-table
paged-attention kernel seam (ISSUE 12).

Oracle strategy, in two layers:

- TOKENS: the non-speculative paged engine (itself pinned against the
  dense engine, transitively against LlamaForCausalLM.generate) is the
  stream reference — greedy speculative decode must reproduce it
  BIT-exactly, because every committed token conditions on a committed
  prefix (the accept rule). A 1-of-2-layer random draft disagrees with
  its target constantly, so these streams exercise rejection mid-window
  and rollback on nearly every step.
- NUMERICS: the pure-jnp tile walk in ``serving_cache.paged_attention``
  is the kernel's oracle — the Pallas kernel runs through the
  interpreter on CPU (skipped, not failed, where Pallas is missing) and
  must agree on every geometry.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (GenerationServer, LlamaDecodeEngine,
                                PagedLlamaDecodeEngine)
from paddle_tpu.serving_cache import PagedKVCache

CFG = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=2, use_flash_attention=False)


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    return LlamaForCausalLM(LlamaConfig.tiny(**CFG))


@pytest.fixture(scope="module")
def paged_ref(model):
    """Non-speculative paged reference engine + memoized greedy
    streams (max_seq 256 so no reference stream truncates early)."""
    eng = PagedLlamaDecodeEngine(model, max_slots=1, max_seq=256,
                                 block_size=8, prefill_chunk=8)
    cache = {}

    def ref(prompt, n_new):
        key = (tuple(int(t) for t in prompt), int(n_new))
        if key not in cache:
            cache[key] = eng.generate(list(key[0]), max_new_tokens=n_new)
        return cache[key]

    return ref


@pytest.fixture(scope="module")
def spec_eng(model):
    """Shared speculative engine: 2 slots over a 64-token paged space,
    8-token blocks/chunks, a truncated-layer draft (1 of 2 layers,
    weight-shared) proposing 3 tokens per step."""
    eng = PagedLlamaDecodeEngine(model, max_slots=2, max_seq=64,
                                 block_size=8, prefill_chunk=8)
    return eng.attach_draft(eng.make_draft(model, num_layers=1),
                            spec_tokens=3)


def _pool_invariants(kv):
    st = kv.stats()
    owned = sum(len(b) for b in kv._owned.values())
    shared = sum(len(b) for b in kv._shared.values())
    # physical partition: every block is free, privately owned, or
    # held by the prefix radix tree (aliased shared blocks live in
    # the tree, counted once however many slots map them)
    assert st["blocks_free"] + owned + st["blocks_cached"] \
        == kv.num_blocks
    assert st["blocks_reserved"] == sum(kv._reserved.values())
    assert st["blocks_available"] >= 0
    mapped = int((kv.block_tables >= 0).sum())
    assert mapped == owned + shared
    # private blocks are exclusive; aliasing may repeat a PHYSICAL
    # block across slots but never within one slot's table
    privs = [b for blks in kv._owned.values() for b in blks]
    assert len(set(privs)) == len(privs)
    for row in kv.block_tables:
        row = row[row >= 0]
        assert len(set(row.tolist())) == len(row)
    kv.check_invariants()


class TestSpecBitEquality:
    def test_server_stream_bit_equal_across_bucketed_prompts(
            self, model, paged_ref, spec_eng):
        """Greedy spec-decode streams through the GenerationServer
        match the non-speculative paged streams token-for-token for
        prompts spanning the prefill buckets; both pools drain clean
        afterwards (accept/rollback leaks nothing)."""
        srv = GenerationServer(spec_eng)
        try:
            for prompt in ([5, 9, 11, 3], [2],
                           [1, 2, 3, 4, 5, 6, 7, 8],
                           list(range(1, 14)), list(range(3, 33))):
                want = paged_ref(prompt, 12)
                got = srv.generate(prompt, 12, timeout=180)
                assert got == want, (len(prompt), got, want)
        finally:
            assert srv.shutdown(drain=True, timeout=120)
        _pool_invariants(spec_eng._kv)
        _pool_invariants(spec_eng._draft._kv)
        assert spec_eng._kv.stats()["blocks_used"] == 0
        assert spec_eng._draft._kv.stats()["blocks_used"] == 0

    def test_spec_step_rejection_rolls_back_with_invariants(
            self, model, paged_ref, spec_eng):
        """Driving spec_step directly: the committed stream continues
        the reference exactly while the allocator invariants (no
        double-ownership, reservation balance, no aliasing) hold
        after EVERY window — including the constant mid-window
        rejections a random 1-layer draft produces."""
        from paddle_tpu.observability import metrics as om

        prompt = [5, 9, 11, 3]
        want = paged_ref(prompt, 16)
        out = [spec_eng.prefill(0, prompt, budget=20)]
        before = dict(om.snapshot().get("serving", {}))
        rejected_windows = 0
        while len(out) < 16:
            toks, counts = spec_eng.spec_step()
            m = int(counts[0])
            if m < spec_eng._spec_k:
                rejected_windows += 1
            out.extend(int(t) for t in toks[0, :m])
            _pool_invariants(spec_eng._kv)
            _pool_invariants(spec_eng._draft._kv)
        spec_eng.release(0)
        assert out[:16] == want, (out, want)
        after = dict(om.snapshot().get("serving", {}))
        steps = after.get("spec_steps_total", 0) - \
            before.get("spec_steps_total", 0)
        assert steps >= 1
        # per-step counters moved: proposed = k * steps, and the
        # rejections above rolled real blocks back
        assert after.get("spec_proposed_total", 0) - \
            before.get("spec_proposed_total", 0) == \
            spec_eng._spec_k * steps
        if rejected_windows:
            assert after.get("spec_rolled_back_total", 0) >= \
                before.get("spec_rolled_back_total", 0)
        _pool_invariants(spec_eng._kv)
        assert spec_eng._kv.stats()["blocks_used"] == 0

    def test_capacity_fallback_mixes_plain_and_spec_steps(
            self, model, paged_ref):
        """When an active slot is within spec_k of capacity the server
        drops to plain single-token steps for that iteration (the
        draft cache develops holes — its proposals degrade, but the
        target's verify stays authoritative), then resumes
        speculating: the stream stays bit-correct through the mix."""
        eng = PagedLlamaDecodeEngine(model, max_slots=2, max_seq=40,
                                     block_size=8, prefill_chunk=8)
        eng.attach_draft(eng.make_draft(model, num_layers=1),
                         spec_tokens=4)
        srv = GenerationServer(eng)
        try:
            prompt = [5, 9, 11, 3]
            want = paged_ref(prompt, 30)
            got = srv.generate(prompt, 30, timeout=180)
            # capacity (max_seq 40) may cut the stream short; every
            # delivered token must continue the reference exactly
            assert len(got) >= 25
            assert got == want[:len(got)], (got, want)
        finally:
            assert srv.shutdown(drain=True, timeout=120)
        assert eng._kv.stats()["blocks_used"] == 0
        assert eng._draft._kv.stats()["blocks_used"] == 0

    def test_draft_shares_target_weights(self, model, spec_eng):
        """make_draft is a truncated-layer VIEW: every retained weight
        is the target's own device array, never a copy."""
        draft = spec_eng._draft
        assert draft.n_layers == 1
        assert draft.params["emb"] is spec_eng.params["emb"]
        assert draft.params["head"] is spec_eng.params["head"]
        assert draft.params["layers"][0]["q_proj"] is \
            spec_eng.params["layers"][0]["q_proj"]

    def test_attach_draft_requires_idle_engine(self, model):
        """A request admitted BEFORE attachment has no spec_k margin
        and no mirrored draft slot — attaching then would exhaust
        mid-decode, so attach_draft refuses until the engine drains."""
        eng = PagedLlamaDecodeEngine(model, max_slots=1, max_seq=64,
                                     block_size=8)
        eng.prefill(0, [1, 2, 3], budget=8)
        with pytest.raises(ValueError, match="IDLE"):
            eng.attach_draft(eng.make_draft(model, num_layers=1),
                             spec_tokens=2)
        eng.release(0)
        eng.attach_draft(eng.make_draft(model, num_layers=1),
                         spec_tokens=2)
        assert eng.generate([1, 2, 3], max_new_tokens=4)  # now fine

    def test_admission_reserves_spec_margin(self, model):
        """With a draft attached, admission reserves spec_k extra
        tokens of budget so window pre-extension can never out-draw
        the reservation."""
        eng = PagedLlamaDecodeEngine(model, max_slots=1, max_seq=64,
                                     block_size=8, num_blocks=8)
        eng.attach_draft(eng.make_draft(model, num_layers=1),
                         spec_tokens=3)
        assert eng.begin_request(0, [1, 2, 3], 8)
        # 3 prompt tokens -> 1 block now; 3+8+3=14 tokens -> 2 blocks
        # total reserved beyond the mapped one
        assert eng._kv.stats()["blocks_reserved"] == 1
        assert eng._draft._kv.stats()["blocks_reserved"] == 1
        eng.release(0)


class TestTruncateRollback:
    def test_truncate_recredits_reservation(self):
        kv = PagedKVCache(max_slots=2, max_seq=64, block_size=8,
                          num_blocks=8)
        assert kv.admit(0, 4, 40)          # 1 mapped + 4 reserved
        kv.ensure_token(0, 8)
        kv.ensure_token(0, 16)             # 2 drawn from reservation
        assert kv.stats()["blocks_used"] == 3
        assert kv.stats()["blocks_reserved"] == 2
        rolled = kv.truncate(0, 9)         # keep positions [0, 9)
        assert rolled == 1
        st = kv.stats()
        assert st["blocks_used"] == 2
        assert st["blocks_reserved"] == 3  # re-credited
        assert st["blocks_free"] >= st["blocks_reserved"]
        kv.ensure_token(0, 16)             # re-draw after rollback
        assert kv.stats()["blocks_used"] == 3
        kv.release(0)
        st = kv.stats()
        assert st["blocks_used"] == 0 and st["blocks_reserved"] == 0
        assert (kv.block_tables == -1).all()

    def test_truncate_noops(self):
        kv = PagedKVCache(max_slots=2, max_seq=64, block_size=8,
                          num_blocks=8)
        assert kv.truncate(0, 8) == 0      # nothing admitted
        kv.admit(1, 8, 8)
        assert kv.truncate(1, 8) == 0      # nothing past the kept end
        kv.release(1)


class TestPagedAttentionKernelSeam:
    """Kernel-vs-oracle parity at the flat seam, via the Pallas
    interpreter on CPU (skipped where Pallas is unavailable)."""

    def _geometries(self):
        # (S, T, H, KVH, D, block_size, max_blocks)
        return [
            (2, 1, 4, 2, 8, 8, 4),     # decode step, GQA
            (3, 5, 4, 2, 8, 8, 4),     # verify window, GQA
            (2, 4, 4, 4, 16, 4, 6),    # MHA (n_rep=1), small blocks
            (1, 8, 2, 1, 8, 16, 2),    # single slot, deep tiles
        ]

    def _case(self, S, T, H, K, D, bs, MB, quant, seed):
        import jax.numpy as jnp
        from paddle_tpu.serving_cache import (absmax_quantize,
                                              paged_attention)
        rng = np.random.default_rng(seed)
        NB = S * MB + 2
        q = jnp.asarray(rng.standard_normal((S, T, H, D)), jnp.float32)
        kp = jnp.asarray(rng.standard_normal((NB, bs, K, D)),
                         jnp.float32)
        vp = jnp.asarray(rng.standard_normal((NB, bs, K, D)),
                         jnp.float32)
        tables = rng.permutation(NB)[:S * MB].reshape(S, MB)
        tables = jnp.asarray(tables.astype(np.int32))
        tables = tables.at[0, MB - 1].set(-1)   # unmapped tail
        pos = jnp.asarray(
            rng.integers(0, bs * MB - T, (S, 1)).astype(np.int32)
            + np.arange(T, dtype=np.int32)[None, :])
        kw = dict(block_size=bs, n_rep=H // K)
        if quant:
            kq, ks = absmax_quantize(kp.reshape(NB * bs, K, D))
            vq, vs = absmax_quantize(vp.reshape(NB * bs, K, D))
            kw.update(k_scale=ks.reshape(NB, bs, K),
                      v_scale=vs.reshape(NB, bs, K))
            kp = kq.reshape(NB, bs, K, D)
            vp = vq.reshape(NB, bs, K, D)
        return q, kp, vp, tables, pos, kw

    def test_kernel_matches_jnp_walk_on_every_geometry(self):
        from paddle_tpu.ops.pallas import paged_attention as pk
        if not pk._HAS_PALLAS:
            pytest.skip("Pallas unavailable — jnp walk is the only "
                        "path (skipped, not failed)")
        import jax.numpy as jnp
        from paddle_tpu.serving_cache import paged_attention
        for i, geo in enumerate(self._geometries()):
            for quant in (False, True):
                q, kp, vp, tables, pos, kw = self._case(
                    *geo, quant=quant, seed=i)
                ref = paged_attention(q, kp, vp, tables, pos,
                                      use_kernel=False, **kw)
                got = pk.paged_attention_kernel(
                    q, kp, vp, tables, pos, interpret=True, **kw)
                np.testing.assert_allclose(
                    np.asarray(ref), np.asarray(got), rtol=1e-6,
                    atol=1e-6, err_msg=f"geometry {geo} quant={quant}")

    def test_kernel_sanitizes_recycled_garbage(self):
        """The MASKED-garbage contract, kernel side: an unmapped
        table entry (-1) clamps its gather to physical block 0 — fill
        block 0 with NaN/inf and keep every position below the
        unmapped tile, and the clamped garbage must contribute
        exactly zero (finite output, bit-matching the jnp walk's
        sanitized result)."""
        from paddle_tpu.ops.pallas import paged_attention as pk
        if not pk._HAS_PALLAS:
            pytest.skip("Pallas unavailable")
        import jax.numpy as jnp
        from paddle_tpu.serving_cache import paged_attention
        rng = np.random.default_rng(9)
        S, T, H, K, D, bs, MB = 2, 1, 4, 2, 8, 8, 4
        NB = S * MB + 2
        q = jnp.asarray(rng.standard_normal((S, T, H, D)), jnp.float32)
        kp = jnp.asarray(rng.standard_normal((NB, bs, K, D)),
                         jnp.float32)
        vp = jnp.asarray(rng.standard_normal((NB, bs, K, D)),
                         jnp.float32)
        # block 0 is nobody's block: tables draw from [1, NB), the
        # last logical tile of each slot is unmapped (-1 -> clamps to
        # the poisoned block 0), and positions stop before that tile
        tables = 1 + rng.permutation(NB - 1)[:S * MB].reshape(S, MB)
        tables = jnp.asarray(tables.astype(np.int32))
        tables = tables.at[:, MB - 1].set(-1)
        pos = jnp.asarray(
            rng.integers(0, bs * (MB - 1) - T, (S, 1)).astype(np.int32)
            + np.arange(T, dtype=np.int32)[None, :])
        kp = kp.at[0].set(jnp.nan)
        vp = vp.at[0].set(jnp.inf)
        kw = dict(block_size=bs, n_rep=H // K)
        ref = paged_attention(q, kp, vp, tables, pos,
                              use_kernel=False, **kw)
        got = pk.paged_attention_kernel(q, kp, vp, tables, pos,
                                        interpret=True, **kw)
        assert bool(jnp.isfinite(got).all())
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                                   rtol=1e-6, atol=1e-6)

    def test_flag_kills_kernel_path(self):
        """FLAGS_paged_attention_kernel=0 forces the jnp walk
        everywhere regardless of backend."""
        from paddle_tpu.serving_cache import use_kernel_default
        paddle.set_flags({"FLAGS_paged_attention_kernel": 0})
        try:
            assert use_kernel_default() is False
        finally:
            paddle.set_flags({"FLAGS_paged_attention_kernel": 1})


class TestJaxprPins:
    def _walk_shapes(self, jaxpr):
        import jax
        shapes = []

        def walk(jx):
            for eqn in jx.eqns:
                for v in eqn.outvars:
                    shapes.append(
                        (eqn.primitive.name,
                         tuple(getattr(v.aval, "shape", ()))))
                for p in eqn.params.values():
                    for sub in (p if isinstance(p, (list, tuple))
                                else [p]):
                        if isinstance(sub, jax.core.Jaxpr):
                            walk(sub)
                        elif isinstance(sub, jax.core.ClosedJaxpr):
                            walk(sub.jaxpr)

        walk(jaxpr.jaxpr)
        return shapes

    def test_dense_decode_no_trailing_max_seq_intermediate(self,
                                                           model):
        """Satellite pin: routing the dense engine's attention through
        the paged_attention seam removed the [*, max_seq]-trailing
        score rows (and the col_mask) from the dense decode step —
        the cache arrays themselves keep max_seq at axis 1, which is
        the dense layout's contract, so the pin is on the TRAILING
        axis where score rows and masks lived."""
        import jax
        import jax.numpy as jnp

        max_seq = 48
        eng = LlamaDecodeEngine(model, max_slots=3, max_seq=max_seq)
        args = (eng.params, eng.k_cache, eng.v_cache,
                jnp.asarray(eng.last_ids), jnp.asarray(eng.pos))
        jaxpr = jax.make_jaxpr(eng._decode_impl)(*args)
        offenders = [(p, s) for p, s in self._walk_shapes(jaxpr)
                     if s and s[-1] == max_seq]
        assert offenders == [], offenders

    def test_spec_verify_no_dense_view(self, model, spec_eng):
        """The batched verify step obeys the same pin as the decode
        step: no [*, max_seq]-shaped intermediate anywhere (max_seq
        64 collides with CFG's vocab_size — use a 48-token engine)."""
        import jax
        import jax.numpy as jnp

        max_seq = 48
        eng = PagedLlamaDecodeEngine(model, max_slots=2,
                                     max_seq=max_seq, block_size=16)
        eng.attach_draft(eng.make_draft(model, num_layers=1),
                         spec_tokens=3)
        k = eng._spec_k
        args = (eng.params, eng.kvs, jnp.asarray(eng.last_ids),
                jnp.zeros((2, k), jnp.int32), jnp.asarray(eng.pos),
                jnp.asarray(eng._kv.block_tables),
                jnp.asarray(eng.active))
        jaxpr = jax.make_jaxpr(eng._spec_verify_impl)(*args)
        offenders = [(p, s) for p, s in self._walk_shapes(jaxpr)
                     if max_seq in s]
        assert offenders == [], offenders

    def test_kernel_path_jaxpr_no_dense_view(self, model,
                                             monkeypatch):
        """The acceptance pin holds on the KERNEL path too: with the
        seam forced to the Pallas kernel, the paged decode step's
        jaxpr (pallas_call inner jaxpr included) still carries no
        [*, max_seq]-shaped intermediate."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu import serving_cache
        from paddle_tpu.ops.pallas import paged_attention as pk
        if not pk._HAS_PALLAS:
            pytest.skip("Pallas unavailable")
        monkeypatch.setattr(serving_cache, "use_kernel_default",
                            lambda: True)
        max_seq = 48
        eng = PagedLlamaDecodeEngine(model, max_slots=3,
                                     max_seq=max_seq, block_size=16)
        args = (eng.params, eng.kvs, jnp.asarray(eng.last_ids),
                jnp.asarray(eng.pos),
                jnp.asarray(eng._kv.block_tables),
                jnp.asarray(eng.active))
        jaxpr = jax.make_jaxpr(eng._decode_impl)(*args)
        offenders = [(p, s) for p, s in self._walk_shapes(jaxpr)
                     if max_seq in s]
        assert offenders == [], offenders


class TestSpecCapture:
    def test_spec_step_audits_zero_syncs(self, model):
        """Steady-state speculative step: the draft-propose and
        batched-verify executables run 0 host syncs and both count
        into sot.captured_steps_total — the PR 10/11 pin extended
        over the spec pair (the window fetch + accept/rollback
        bookkeeping live OUTSIDE the audited region by design: they
        are the capture boundary, allowlisted as such)."""
        import jax.numpy as jnp
        from paddle_tpu import analysis
        from paddle_tpu.observability import metrics as om

        eng = PagedLlamaDecodeEngine(model, max_slots=2, max_seq=64,
                                     block_size=8)
        eng.attach_draft(eng.make_draft(model, num_layers=1),
                         spec_tokens=2)
        eng.prefill(0, [1, 2, 3], budget=30)
        eng.prefill(1, [4, 5], budget=30)
        for _ in range(2):                 # warm + steady state
            eng.spec_step()
        draft, k = eng._draft, eng._spec_k

        def one_spec_step():
            for s in range(eng.max_slots):
                if eng.active[s]:
                    eng._kv.reserve_through(s, int(eng.pos[s]) + k)
                    draft._kv.reserve_through(
                        s, int(eng.pos[s]) + k - 1)
            last = jnp.asarray(eng.last_ids)
            pos = jnp.asarray(eng.pos)
            act = jnp.asarray(eng.active)
            dtok, draft.kvs = eng._spec_propose(
                draft.params, draft.kvs, last, pos,
                jnp.asarray(draft._kv.block_tables), act)
            t, n_acc, eng.kvs = eng._spec_verify(
                eng.params, eng.kvs, last, dtok, pos,
                jnp.asarray(eng._kv.block_tables), act)
            return t, n_acc

        before = dict(om.snapshot().get("sot", {}))
        rep = analysis.audit(one_spec_step)
        after = dict(om.snapshot().get("sot", {}))
        assert rep.syncs == [], rep.syncs
        assert not [d for d in rep.diagnostics
                    if d.rule in ("PTA001", "PTA002", "PTA003")], \
            [d.to_dict() for d in rep.diagnostics]
        got = after.get("captured_steps_total", 0) - \
            before.get("captured_steps_total", 0)
        assert got >= 2, (before, after)   # propose AND verify


class TestLoadShedding:
    def test_shed_rejects_when_starved_and_backlogged(self, model):
        """ROADMAP 1c policy: pool exhausted + deferred backlog over
        FLAGS_serving_shed_queue -> submit() rejects immediately with
        reason=shed (counted + flight event) instead of deferring
        unboundedly; in-flight work is untouched and the default
        (flag 0) keeps the pre-policy defer-forever behavior."""
        from paddle_tpu.observability import flight
        from paddle_tpu.observability import metrics as om

        eng = PagedLlamaDecodeEngine(model, max_slots=4, max_seq=64,
                                     block_size=8, num_blocks=4,
                                     prefill_chunk=8)
        orig_step = eng.step

        def slow_step():
            time.sleep(0.02)
            return orig_step()

        eng.step = slow_step
        srv = GenerationServer(eng)
        try:
            # 12 prompt + 20 budget = 32 tokens = the whole 4-block
            # pool (any larger could NEVER fit and fails loudly)
            blocker = srv.submit([1, 2, 3] * 4, 20)
            deferred = [srv.submit([1, 2, 3] * 4, 8)
                        for _ in range(3)]
            for _ in range(300):               # wait for the backlog
                st = srv.stats()
                if st["waiting_for_blocks"] >= 1 \
                        and st["waiting_for_blocks"] + st["queued"] >= 2:
                    break
                time.sleep(0.02)
            st = srv.stats()
            assert st["waiting_for_blocks"] >= 1, st
            assert st["waiting_for_blocks"] + st["queued"] >= 2, st
            paddle.set_flags({"FLAGS_serving_shed_queue": 1})
            before = dict(om.snapshot().get("serving", {}))
            with pytest.raises(RuntimeError, match="shed"):
                srv.submit([7, 8, 9], 4)
            after = dict(om.snapshot().get("serving", {}))
            assert srv.stats()["shed"] == 1
            assert after.get("shed_total", 0) == \
                before.get("shed_total", 0) + 1
            sheds = [e for e in flight.events(category="serving")
                     if e["name"] == "rejected"
                     and e.get("attrs", {}).get("reason") == "shed"]
            assert sheds, "no rejected(reason=shed) flight event"
            # with the policy off, the same submit defers instead
            paddle.set_flags({"FLAGS_serving_shed_queue": 0})
            ok = srv.submit([7, 8, 9], 4)
            assert blocker["done"].wait(180) and \
                blocker["error"] is None
            for r in deferred + [ok]:
                assert r["done"].wait(180)
                assert r["error"] is None, r["error"]
        finally:
            paddle.set_flags({"FLAGS_serving_shed_queue": 0})
            srv.shutdown(drain=True, timeout=120)
        assert eng._kv.stats()["blocks_used"] == 0
