"""Tests for the paddle.static facade long tail (static/extras.py):
gradient machinery over the replay (append_backward/gradients), metrics,
EMA, py_func, persistence, pruning (ref: python/paddle/static/__init__.py
__all__)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


@pytest.fixture
def prog_pair():
    main, startup = static.Program(), static.Program()
    return main, startup


class TestGradientMachinery:
    def test_append_backward_grads_fetchable(self, prog_pair):
        main, startup = prog_pair
        with static.program_guard(main, startup):
            x = static.data("x", [4, 3], "float32")
            w = static.create_parameter([3, 2], "float32")
            w.name = "w0"
            y = paddle.matmul(x, w)
            loss = (y * y).mean()
            pgs = static.append_backward(loss)
        assert len(pgs) == 1
        exe = static.Executor()
        xv = np.random.default_rng(0).standard_normal((4, 3)).astype(
            np.float32)
        out = exe.run(main, feed={"x": xv},
                      fetch_list=[loss, pgs[0][1]])
        loss_v, gw = out
        # oracle: d mean((xw)^2) / dw = 2 x^T (xw) / numel
        wv = np.asarray(w.numpy(), np.float64)
        yv = xv.astype(np.float64) @ wv
        exp = 2.0 * xv.astype(np.float64).T @ yv / yv.size
        np.testing.assert_allclose(gw, exp, rtol=1e-5)
        np.testing.assert_allclose(loss_v, (yv * yv).mean(), rtol=1e-5)

    def test_gradients_wrt_feed_input(self, prog_pair):
        main, startup = prog_pair
        with static.program_guard(main, startup):
            x = static.data("x", [5], "float32")
            y = (x * x).sum()
            (gx,) = static.gradients(y, x)
        exe = static.Executor()
        xv = np.arange(5, dtype=np.float32)
        (g,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
        np.testing.assert_allclose(g, 2 * xv, rtol=1e-6)

    def test_gradients_with_target_gradients(self, prog_pair):
        main, startup = prog_pair
        with static.program_guard(main, startup):
            x = static.data("x", [3], "float32")
            t = x * 2.0
            tg = paddle.to_tensor(np.asarray([1.0, 0.0, 3.0], np.float32))
            (gx,) = static.gradients([t], [x], target_gradients=[tg])
        exe = static.Executor()
        (g,) = exe.run(main, feed={"x": np.ones(3, np.float32)},
                       fetch_list=[gx])
        np.testing.assert_allclose(g, [2.0, 0.0, 6.0], rtol=1e-6)

    def test_gradients_length_mismatch_raises(self, prog_pair):
        main, startup = prog_pair
        with static.program_guard(main, startup):
            x = static.data("x", [3], "float32")
            t1, t2 = x * 2.0, x * 3.0
            tg = paddle.to_tensor(np.ones(3, np.float32))
            with pytest.raises(ValueError, match="1:1"):
                static.gradients([t1, t2], [x], target_gradients=[tg])


class TestMetricsAndOps:
    def test_accuracy(self):
        scores = paddle.to_tensor(np.asarray(
            [[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], np.float32))
        label = paddle.to_tensor(np.asarray([1, 0, 0], np.int64))
        acc = static.accuracy(scores, label, k=1)
        np.testing.assert_allclose(float(acc), 2.0 / 3.0, rtol=1e-6)

    def test_auc_perfect_separation(self):
        scores = paddle.to_tensor(np.asarray(
            [[0.9, 0.1], [0.8, 0.2], [0.2, 0.8], [0.1, 0.9]], np.float32))
        label = paddle.to_tensor(np.asarray([0, 0, 1, 1], np.int64))
        auc_v, batch_auc, stats = static.auc(scores, label)
        assert float(auc_v) > 0.99
        assert len(stats) == 2

    def test_ctr_metric_bundle(self):
        scores = paddle.to_tensor(np.asarray(
            [[0.4, 0.6], [0.7, 0.3]], np.float32))
        label = paddle.to_tensor(np.asarray([1, 0], np.int64))
        vals = static.ctr_metric_bundle(scores, label)
        assert len(vals) == 7
        np.testing.assert_allclose(float(vals[6]), 2.0)  # total
        np.testing.assert_allclose(float(vals[5]), 1.0)  # positives

    def test_py_func_forward_and_backward(self):
        x = paddle.to_tensor(np.asarray([1.0, 2.0, 3.0], np.float32),
                             stop_gradient=False)
        out_t = paddle.to_tensor(np.zeros(3, np.float32))
        y = static.py_func(lambda a: a * 2.0, x, out_t,
                           backward_func=lambda a, g: g * 2.0)
        np.testing.assert_allclose(y.numpy(), [2.0, 4.0, 6.0])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0, 2.0])

    def test_print_is_identity(self, capsys):
        x = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
        y = static.Print(x, message="dbg")
        np.testing.assert_allclose(y.numpy(), x.numpy())


class TestEMA:
    def test_update_apply_restore(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(4, 4)
        ema = static.ExponentialMovingAverage(decay=0.5)
        w0 = lin.weight.numpy().copy()
        ema.update(lin.parameters())
        lin.weight.set_value(paddle.to_tensor(w0 * 3.0))
        ema.update(lin.parameters())
        with ema.apply():
            # shadow after 2 steps: .5*(.5*w0+.5*w0) + ... bias-corrected
            applied = lin.weight.numpy().copy()
            assert not np.allclose(applied, w0 * 3.0)
        np.testing.assert_allclose(lin.weight.numpy(), w0 * 3.0,
                                   rtol=1e-6)


class TestPersistence:
    def test_save_load_roundtrip(self, tmp_path, prog_pair):
        main, startup = prog_pair
        with static.program_guard(main, startup):
            x = static.data("x", [2, 3], "float32")
            w = static.create_parameter([3, 2], "float32")
            w.name = "w_rt"
            y = paddle.matmul(x, w)
        path = str(tmp_path / "m")
        static.save(main, path)
        orig = w.numpy().copy()
        w.set_value(paddle.to_tensor(np.zeros((3, 2), np.float32)))
        static.load(main, path)
        np.testing.assert_allclose(w.numpy(), orig)

    def test_program_state_roundtrip(self, tmp_path, prog_pair):
        main, startup = prog_pair
        with static.program_guard(main, startup):
            x = static.data("x", [2], "float32")
            w = static.create_parameter([2], "float32")
            w.name = "w_ps"
            y = (x * w).sum()
        static.save(main, str(tmp_path / "st"))
        state = static.load_program_state(str(tmp_path / "st"))
        assert "w_ps" in state
        state["w_ps"] = state["w_ps"] + 1.0
        static.set_program_state(main, state)
        np.testing.assert_allclose(
            w.numpy(), np.asarray(state["w_ps"]), rtol=1e-6)

    def test_save_load_file_bytes(self, tmp_path):
        p = str(tmp_path / "blob.bin")
        static.save_to_file(p, b"abc123")
        assert static.load_from_file(p) == b"abc123"

    def test_serialize_deserialize_program(self, prog_pair):
        main, startup = prog_pair
        with static.program_guard(main, startup):
            x = static.data("x", [4], "float32")
            y = x * 2.0 + 1.0
        data = static.serialize_program([x], [y], program=main)
        assert isinstance(data, bytes)
        prog2 = static.deserialize_program(data)
        exe = static.Executor()
        out = exe.run(prog2, feed={"x": np.ones(4, np.float32)},
                      fetch_list=None)
        np.testing.assert_allclose(out[0], 3.0 * np.ones(4), rtol=1e-6)

    def test_serialize_persistables_roundtrip(self, prog_pair):
        main, startup = prog_pair
        with static.program_guard(main, startup):
            x = static.data("x", [2], "float32")
            w = static.create_parameter([2], "float32")
            w.name = "w_sp"
            y = x * w
        blob = static.serialize_persistables([x], [y], program=main)
        orig = w.numpy().copy()
        w.set_value(paddle.to_tensor(np.zeros(2, np.float32)))
        static.deserialize_persistables(main, blob)
        np.testing.assert_allclose(w.numpy(), orig)


class TestProgramUtils:
    def test_normalize_program_prunes(self, prog_pair):
        main, startup = prog_pair
        with static.program_guard(main, startup):
            x = static.data("x", [3], "float32")
            y = x * 2.0
            z = x + 10.0  # dead wrt fetch y
            dead = z * z
        pruned = static.normalize_program(main, [x], [y])
        assert len(pruned.ops) < len(main.ops)
        exe = static.Executor()
        (out,) = exe.run(pruned, feed={"x": np.ones(3, np.float32)},
                         fetch_list=[y])
        np.testing.assert_allclose(out, 2.0 * np.ones(3))

    def test_compiled_program_wraps(self, prog_pair):
        main, startup = prog_pair
        with static.program_guard(main, startup):
            x = static.data("x", [2], "float32")
            y = x + 1.0
        cp = static.CompiledProgram(main,
                                    build_strategy=static.BuildStrategy())
        exe = static.Executor()
        (out,) = exe.run(cp.program, feed={"x": np.zeros(2, np.float32)},
                         fetch_list=[y])
        np.testing.assert_allclose(out, 1.0)

    def test_variable_alias_and_places(self):
        assert static.Variable is paddle.Tensor
        assert len(static.cpu_places(2)) == 2
        assert len(static.cuda_places()) >= 1
        with pytest.raises(NotImplementedError):
            static.xpu_places()
        with pytest.raises(NotImplementedError):
            static.IpuStrategy()

    def test_name_scope_nests(self):
        with static.name_scope("a"):
            with static.name_scope("b") as full:
                assert full == "a/b"

    def test_scope_guard(self):
        from paddle_tpu.static.executor import _Scope
        s = _Scope()
        with static.scope_guard(s):
            assert static.global_scope() is s
        assert static.global_scope() is not s

    def test_device_guard_cpu(self):
        with static.device_guard("cpu"):
            t = paddle.to_tensor(np.ones(2, np.float32))
        assert np.allclose(t.numpy(), 1.0)

    def test_create_global_var(self):
        v = static.create_global_var([2, 2], 3.5, "float32",
                                     persistable=True)
        np.testing.assert_allclose(v.numpy(), 3.5)


class TestJitVisionNameTail:
    def test_enable_to_static_off_returns_fn(self):
        import paddle_tpu.jit as jit

        def f(x):
            return x * 2

        jit.enable_to_static(False)
        try:
            assert jit.to_static(f) is f
        finally:
            jit.enable_to_static(True)
        assert jit.to_static(f) is not f

    def test_verbosity_and_code_level_knobs(self):
        import logging

        import paddle_tpu.jit as jit
        jit.set_verbosity(2)
        assert logging.getLogger(
            "paddle_tpu.jit.dy2static").level == logging.DEBUG
        jit.set_verbosity(0)
        jit.set_code_level(1)
        assert logging.getLogger(
            "paddle_tpu.jit.dy2static.code").level == logging.DEBUG

    def test_translated_layer_from_aot_artifact(self, tmp_path):
        from paddle_tpu.inference import save_inference_model
        from paddle_tpu.jit import TranslatedLayer
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.static import InputSpec

        paddle.seed(0)
        m = LlamaForCausalLM(LlamaConfig.tiny())
        m.eval()
        path = str(tmp_path / "aot_model")
        save_inference_model(path, m, input_spec=[
            InputSpec([1, 8], "int32")], aot=True)
        # TranslatedLayer serves the AOT program with no model class
        tl = TranslatedLayer.load(path)
        ids = paddle.to_tensor(np.random.default_rng(0).integers(
            0, 128, (1, 8)).astype(np.int32))
        np.testing.assert_allclose(tl(ids).numpy(), m(ids).numpy(),
                                   atol=1e-5)
        with pytest.raises(RuntimeError, match="train"):
            tl.train()

    def test_image_backend_helpers(self, tmp_path):
        from PIL import Image

        from paddle_tpu.vision import (get_image_backend, image_load,
                                       set_image_backend)
        p = str(tmp_path / "img.png")
        arr = np.zeros((4, 4, 3), np.uint8)
        arr[..., 0] = 255  # red in RGB
        Image.fromarray(arr).save(p)
        assert get_image_backend() == "pil"
        img = image_load(p)
        assert np.asarray(img).shape == (4, 4, 3)
        set_image_backend("cv2")
        try:
            a = image_load(p)
            assert isinstance(a, np.ndarray)
            assert a[0, 0, 2] == 255  # BGR: red lands in channel 2
        finally:
            set_image_backend("pil")
        with pytest.raises(ValueError):
            set_image_backend("magick")
