"""Numeric tests for the extended optimizer zoo (ASGD/NAdam/RAdam/Rprop/
LBFGS) against NumPy reference implementations of the documented update
equations (ref: python/paddle/optimizer/{asgd,nadam,radam,rprop,lbfgs}.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def _make_param(shape=(3, 4), seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=shape).astype(np.float32)
    p = paddle.Parameter(paddle.to_tensor(w.copy())._data)
    return p, w


def _grads(n, shape=(3, 4), seed=1):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=shape).astype(np.float32) for _ in range(n)]


def _run(opt, p, grads):
    for g in grads:
        p.grad = paddle.to_tensor(g)
        opt.step()
        opt.clear_grad()
    return p.numpy()


class TestASGD:
    def test_matches_reference_equations(self):
        n = 3
        p, w = _make_param()
        grads = _grads(5)
        opt = paddle.optimizer.ASGD(learning_rate=0.1, batch_num=n,
                                    parameters=[p])
        got = _run(opt, p, grads)

        d = np.zeros_like(w)
        ys = np.zeros((n,) + w.shape, np.float32)
        x = w.copy()
        for m, g in enumerate(grads):
            i = m % n
            d = d - ys[i] + g
            ys[i] = g
            x = x - 0.1 * (d / min(m + 1, n))
        np.testing.assert_allclose(got, x, rtol=1e-5, atol=1e-6)

    def test_batch_num_validation(self):
        p, _ = _make_param()
        with pytest.raises(ValueError):
            paddle.optimizer.ASGD(batch_num=0, parameters=[p])


class TestNAdam:
    def test_matches_reference_equations(self):
        b1, b2, eps, psi, lr = 0.9, 0.999, 1e-8, 0.004, 0.01
        p, w = _make_param()
        grads = _grads(4)
        opt = paddle.optimizer.NAdam(learning_rate=lr, beta1=b1, beta2=b2,
                                     epsilon=eps, parameters=[p])
        got = _run(opt, p, grads)

        m = np.zeros_like(w)
        v = np.zeros_like(w)
        mu_prod = 1.0
        x = w.copy()
        for t, g in enumerate(grads, start=1):
            mu_t = b1 * (1 - 0.5 * 0.96 ** (t * psi))
            mu_t1 = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * psi))
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mu_prod_t = mu_prod * mu_t
            mu_prod_t1 = mu_prod_t * mu_t1
            m_hat = mu_t1 * m / (1 - mu_prod_t1) + \
                (1 - mu_t) * g / (1 - mu_prod_t)
            v_hat = v / (1 - b2 ** t)
            x = x - lr * m_hat / (np.sqrt(v_hat) + eps)
            mu_prod = mu_prod_t
        np.testing.assert_allclose(got, x, rtol=1e-5, atol=1e-6)


class TestRAdam:
    def test_matches_reference_equations(self):
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 0.01
        p, w = _make_param()
        grads = _grads(8)
        opt = paddle.optimizer.RAdam(learning_rate=lr, beta1=b1, beta2=b2,
                                     epsilon=eps, parameters=[p])
        got = _run(opt, p, grads)

        m = np.zeros_like(w)
        v = np.zeros_like(w)
        x = w.copy()
        rho_inf = 2 / (1 - b2) - 1
        # beta powers accumulate in float32 state (like the impl / reference
        # accumulators), which matters because 1 - beta2^t cancels
        b1p = np.float32(1.0)
        b2p = np.float32(1.0)
        for t, g in enumerate(grads, start=1):
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            b1p = np.float32(b1p * np.float32(b1))
            b2p = np.float32(b2p * np.float32(b2))
            rho_t = rho_inf - 2 * t * b2p / (1 - b2p)
            m_hat = m / (1 - b1p)
            if rho_t > 5:
                r_t = np.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf) /
                              ((rho_inf - 4) * (rho_inf - 2) * rho_t))
                x = x - lr * m_hat * r_t / (np.sqrt(v / (1 - b2p)) + eps)
            else:
                x = x - lr * m_hat
        np.testing.assert_allclose(got, x, rtol=1e-5, atol=1e-6)

    def test_early_steps_unrectified(self):
        """rho_t <= 5 for the first few steps -> plain momentum update."""
        p, w = _make_param()
        g = _grads(1)[0]
        opt = paddle.optimizer.RAdam(learning_rate=0.01, parameters=[p])
        p.grad = paddle.to_tensor(g)
        opt.step()
        expect = w - 0.01 * g  # m_hat == g at t=1
        np.testing.assert_allclose(p.numpy(), expect, rtol=1e-5, atol=1e-6)


class TestRprop:
    def test_matches_reference_equations(self):
        lr, lo, hi, etas = 0.01, 1e-5, 50.0, (0.5, 1.2)
        p, w = _make_param()
        grads = _grads(6)
        opt = paddle.optimizer.Rprop(learning_rate=lr,
                                     learning_rate_range=(lo, hi),
                                     etas=etas, parameters=[p])
        got = _run(opt, p, grads)

        prev = np.zeros_like(w)
        step = np.full_like(w, lr)
        x = w.copy()
        for g in grads:
            sign = g * prev
            factor = np.where(sign > 0, etas[1],
                              np.where(sign < 0, etas[0], 1.0))
            step = np.clip(step * factor, lo, hi)
            g_eff = np.where(sign < 0, 0.0, g)
            x = x - np.sign(g_eff) * step
            prev = g_eff
        np.testing.assert_allclose(got, x, rtol=1e-5, atol=1e-6)

    def test_validation(self):
        p, _ = _make_param()
        with pytest.raises(ValueError):
            paddle.optimizer.Rprop(learning_rate=100.0, parameters=[p])
        with pytest.raises(ValueError):
            paddle.optimizer.Rprop(etas=(1.5, 1.2), parameters=[p])


class TestLBFGS:
    def _quadratic_problem(self):
        """min 0.5 ||A x - b||^2 — LBFGS should converge fast."""
        rng = np.random.default_rng(7)
        A = rng.normal(size=(6, 4)).astype(np.float32)
        b = rng.normal(size=(6,)).astype(np.float32)
        x0 = np.zeros((4,), np.float32)
        p = paddle.Parameter(paddle.to_tensor(x0)._data)
        A_t = paddle.to_tensor(A)
        b_t = paddle.to_tensor(b)

        def closure():
            r = paddle.matmul(A_t, p) - b_t
            loss = (r * r).sum() * 0.5
            p.clear_gradient()
            loss.backward()
            return loss

        x_star = np.linalg.lstsq(A, b, rcond=None)[0]
        return p, closure, x_star

    def test_converges_plain(self):
        p, closure, x_star = self._quadratic_problem()
        opt = paddle.optimizer.LBFGS(learning_rate=0.5, max_iter=50,
                                     parameters=[p])
        for _ in range(5):
            opt.step(closure)
        np.testing.assert_allclose(p.numpy(), x_star, rtol=1e-2, atol=1e-2)

    def test_converges_strong_wolfe(self):
        p, closure, x_star = self._quadratic_problem()
        opt = paddle.optimizer.LBFGS(learning_rate=1.0, max_iter=30,
                                     line_search_fn="strong_wolfe",
                                     parameters=[p])
        opt.step(closure)
        np.testing.assert_allclose(p.numpy(), x_star, rtol=1e-3, atol=1e-3)

    def test_requires_closure(self):
        p, _ = _make_param()
        opt = paddle.optimizer.LBFGS(parameters=[p])
        with pytest.raises(ValueError):
            opt.step()
