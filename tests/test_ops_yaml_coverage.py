"""Per-op numeric + gradient coverage driven by the ops.yaml table.

ref: test/legacy_test/op_test.py:418 (NumPy-reference check_output
:2139 + finite-difference check_grad :3129, per-op exemption lists in
test/white_list/). This harness walks the SAME YAML table the native
OpRegistry loads, so every declared op either has a numeric spec here or
sits on the explicit exemption list (asserted at the bottom — adding an
op to ops.yaml without covering it fails the suite).
"""
import numpy as np
import pytest
import yaml

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor

RNG = np.random.default_rng(1234)


def _pos(*s):
    return (RNG.random(s) + 0.5).astype(np.float32)


def _unit(*s):
    return (RNG.random(s) * 1.6 - 0.8).astype(np.float32)


def _std(*s):
    return RNG.standard_normal(s).astype(np.float32)


def _ints(hi, *s):
    return RNG.integers(0, hi, s).astype(np.int64)


def _bools(*s):
    return RNG.random(s) > 0.5


# spec: name -> (inputs_fn, attrs, numpy_ref, check_grad)
SPECS = {}


def spec(name, inputs_fn, ref, attrs=None, grad=True):
    SPECS[name] = (inputs_fn, attrs or {}, ref, grad)


import scipy.special as sps  # noqa: E402
import scipy.linalg  # noqa: E402,F401


# -- unary math (numpy-identical) -------------------------------------------
_UNARY = {
    "abs": (np.abs, _std), "acos": (np.arccos, _unit),
    "acosh": (np.arccosh, lambda *s: _pos(*s) + 1.0),
    "asin": (np.arcsin, _unit), "asinh": (np.arcsinh, _std),
    "atan": (np.arctan, _std), "atanh": (np.arctanh, _unit),
    "ceil": (np.ceil, _std), "cos": (np.cos, _std),
    "cosh": (np.cosh, _std), "erf": (sps.erf, _std),
    "erfinv": (sps.erfinv, _unit), "exp": (np.exp, _std),
    "expm1": (np.expm1, _std), "floor": (np.floor, _std),
    "lgamma": (sps.gammaln, _pos), "log": (np.log, _pos),
    "log10": (np.log10, _pos), "log1p": (np.log1p, _pos),
    "log2": (np.log2, _pos), "neg": (np.negative, _std),
    "reciprocal": (np.reciprocal, _pos), "round": (np.round, _std),
    "rsqrt": (lambda x: 1 / np.sqrt(x), _pos),
    "sigmoid": (lambda x: 1 / (1 + np.exp(-x)), _std),
    "sign": (np.sign, _std), "sin": (np.sin, _std),
    "sinh": (np.sinh, _std), "sqrt": (np.sqrt, _pos),
    "square": (np.square, _std), "tan": (np.tan, _unit),
    "tanh": (np.tanh, _std), "trunc": (np.trunc, _std),
    "digamma": (sps.digamma, _pos),
    "frac": (lambda x: x - np.trunc(x), _std),
    "real": (np.real, _std), "conj": (np.conj, _std),
    "angle": (np.angle, _std), "imag": (np.imag, _std),
}
_NO_GRAD_UNARY = {"ceil", "floor", "round", "sign", "trunc", "frac",
                  "angle", "imag"}
for n, (ref, gen) in _UNARY.items():
    spec(n, lambda gen=gen: [gen(3, 4)], (lambda ref: lambda x: ref(x))(ref),
         grad=n not in _NO_GRAD_UNARY)

spec("stanh", lambda: [_std(3, 4)],
     lambda x, scale_a=0.67, scale_b=1.7159: scale_b * np.tanh(x * scale_a))
spec("scale", lambda: [_std(3, 4)],
     lambda x, scale=2.0, bias=1.0: x * 2.0 + 1.0,
     attrs={"scale": 2.0, "bias": 1.0})
spec("clip", lambda: [_std(3, 4)],
     lambda x, min=-0.5, max=0.5: np.clip(x, -0.5, 0.5),
     attrs={"min": -0.5, "max": 0.5})
spec("isnan", lambda: [np.array([1.0, np.nan, np.inf], np.float32)],
     np.isnan, grad=False)
spec("isinf", lambda: [np.array([1.0, np.nan, np.inf], np.float32)],
     np.isinf, grad=False)
spec("isfinite", lambda: [np.array([1.0, np.nan, np.inf], np.float32)],
     np.isfinite, grad=False)
spec("sinc", lambda: [_std(3, 4)], np.sinc)
spec("copysign", lambda: [_std(3, 4), _std(3, 4)], np.copysign,
     grad=False)
spec("rad2deg", lambda: [_std(3, 4)], np.rad2deg)
spec("deg2rad", lambda: [_std(3, 4)], np.deg2rad)

# -- binary math -------------------------------------------------------------
_BINARY = {
    "add": np.add, "subtract": np.subtract, "multiply": np.multiply,
    "divide": lambda a, b: a / b, "maximum": np.maximum,
    "minimum": np.minimum, "fmax": np.fmax, "fmin": np.fmin,
    "atan2": np.arctan2, "hypot": np.hypot,
    "logaddexp": np.logaddexp,
}
for n, ref in _BINARY.items():
    spec(n, lambda: [_std(3, 4), _pos(3, 4)],
         (lambda r: lambda a, b: r(a, b))(ref))
spec("pow", lambda: [_pos(3, 4), np.float32(2.5)],
     lambda a, b: np.power(a, b))
spec("mod", lambda: [_std(3, 4), _pos(3, 4)], np.mod, grad=False)
spec("remainder", lambda: [_std(3, 4), _pos(3, 4)], np.remainder,
     grad=False)
spec("floor_mod", lambda: [_std(3, 4), _pos(3, 4)], np.mod, grad=False)
spec("floor_divide", lambda: [_std(3, 4), _pos(3, 4)], np.floor_divide,
     grad=False)
spec("lerp", lambda: [_std(3, 4), _std(3, 4), np.float32(0.3)],
     lambda a, b, w: a + 0.3 * (b - a))

for n, ref in {"equal": np.equal, "not_equal": np.not_equal,
               "greater_than": np.greater, "greater_equal": np.greater_equal,
               "less_than": np.less, "less_equal": np.less_equal}.items():
    spec(n, lambda: [_ints(3, 4, 4), _ints(3, 4, 4)],
         (lambda r: lambda a, b: r(a, b))(ref), grad=False)
for n, ref in {"logical_and": np.logical_and,
               "logical_or": np.logical_or,
               "logical_xor": np.logical_xor}.items():
    spec(n, lambda: [_bools(4, 4), _bools(4, 4)],
         (lambda r: lambda a, b: r(a, b))(ref), grad=False)
spec("logical_not", lambda: [_bools(4, 4)], np.logical_not, grad=False)
for n, ref in {"bitwise_and": np.bitwise_and, "bitwise_or": np.bitwise_or,
               "bitwise_xor": np.bitwise_xor}.items():
    spec(n, lambda: [_ints(16, 3, 4), _ints(16, 3, 4)],
         (lambda r: lambda a, b: r(a, b))(ref), grad=False)
spec("bitwise_not", lambda: [_ints(16, 3, 4)], np.bitwise_not, grad=False)
spec("allclose", lambda: [_std(3, 4)] * 2,
     lambda a, b: np.allclose(a, b), grad=False)
spec("isclose", lambda: [_std(3, 4)] * 2, np.isclose, grad=False)
spec("equal_all", lambda: [_ints(3, 2, 2), _ints(3, 2, 2)],
     lambda a, b: np.array_equal(a, b), grad=False)

# -- reductions --------------------------------------------------------------
spec("sum", lambda: [_std(3, 4)], lambda x, axis=1: x.sum(1),
     attrs={"axis": 1})
spec("mean", lambda: [_std(3, 4)], lambda x, axis=1: x.mean(1),
     attrs={"axis": 1})
spec("squared_l2_norm", lambda: [_std(3, 4)], lambda x: np.sum(x * x))
spec("cast", lambda: [_std(3, 4)],
     lambda x, dtype=None: x.astype(np.float32),
     attrs={"dtype": "float32"})
spec("prod", lambda: [_pos(3, 4)], lambda x, axis=1: x.prod(1),
     attrs={"axis": 1})
spec("max", lambda: [_std(3, 4)], lambda x, axis=1: x.max(1),
     attrs={"axis": 1})
spec("min", lambda: [_std(3, 4)], lambda x, axis=1: x.min(1),
     attrs={"axis": 1})
spec("amax", lambda: [_std(3, 4)], lambda x, axis=1: x.max(1),
     attrs={"axis": 1})
spec("amin", lambda: [_std(3, 4)], lambda x, axis=1: x.min(1),
     attrs={"axis": 1})
spec("std", lambda: [_std(5, 6)], lambda x: x.std(ddof=1))
spec("var", lambda: [_std(5, 6)], lambda x: x.var(ddof=1))
spec("median", lambda: [_std(3, 5)], lambda x: np.median(x), grad=False)
spec("logsumexp", lambda: [_std(3, 4)],
     lambda x: sps.logsumexp(x.astype(np.float64)).astype(np.float32))
spec("nanmean", lambda: [np.where(_bools(4, 4), _std(4, 4),
                                  np.nan).astype(np.float32)],
     np.nanmean, grad=False)
spec("nansum", lambda: [np.where(_bools(4, 4), _std(4, 4),
                                 np.nan).astype(np.float32)],
     np.nansum, grad=False)
spec("all", lambda: [_bools(3, 4)], np.all, grad=False)
spec("any", lambda: [_bools(3, 4)], np.any, grad=False)
spec("count_nonzero", lambda: [_ints(2, 4, 4)],
     lambda x: np.count_nonzero(x), grad=False)
spec("cumsum", lambda: [_std(3, 4)], lambda x, axis=1: x.cumsum(1),
     attrs={"axis": 1})
spec("cumprod", lambda: [_pos(3, 4)], lambda x, dim=1: x.cumprod(1),
     attrs={"dim": 1})
spec("cummax", lambda: [_std(3, 4)],
     lambda x, axis=1: np.maximum.accumulate(x, 1), attrs={"axis": 1},
     grad=False)
spec("cummin", lambda: [_std(3, 4)],
     lambda x, axis=1: np.minimum.accumulate(x, 1), attrs={"axis": 1},
     grad=False)
spec("argmax", lambda: [_std(3, 4)], lambda x, axis=1: x.argmax(1),
     attrs={"axis": 1}, grad=False)
spec("argmin", lambda: [_std(3, 4)], lambda x, axis=1: x.argmin(1),
     attrs={"axis": 1}, grad=False)
spec("argsort", lambda: [_std(3, 4)], lambda x, axis=1: x.argsort(1),
     attrs={"axis": 1}, grad=False)
spec("sort", lambda: [_std(3, 4)], lambda x, axis=1: np.sort(x, 1),
     attrs={"axis": 1})
spec("bincount", lambda: [_ints(6, 20)],
     lambda x: np.bincount(x), grad=False)
spec("nonzero", lambda: [np.asarray([[1, 0], [0, 2]], np.float32)],
     lambda x: np.stack(np.nonzero(x), 1), grad=False)
spec("searchsorted", lambda: [np.sort(_std(8)), _std(5)],
     lambda a, v: np.searchsorted(a, v), grad=False)
spec("unique", lambda: [_ints(5, 12)], np.unique, grad=False)
spec("kthvalue",
     lambda: [_std(3, 6)],
     lambda x, k=2, axis=1: np.partition(x, 1, axis=1)[:, 1],
     attrs={"k": 2, "axis": 1}, grad=False)
spec("mode", lambda: [np.asarray([[1., 1., 2.], [3., 3., 1.]],
                                 np.float32)],
     lambda x: np.asarray([1., 3.], np.float32), grad=False)
spec("topk", lambda: [_std(3, 6)],
     lambda x, k=2: -np.sort(-x, axis=-1)[:, :2],
     attrs={"k": 2}, grad=False)
spec("index_sample", lambda: [_std(3, 6), _ints(6, 3, 2)],
     lambda x, idx: np.take_along_axis(x, idx, 1), grad=False)

# -- linalg ------------------------------------------------------------------
spec("matmul", lambda: [_std(3, 4), _std(4, 5)], lambda a, b: a @ b)
spec("mm", lambda: [_std(3, 4), _std(4, 5)], lambda a, b: a @ b)
spec("bmm", lambda: [_std(2, 3, 4), _std(2, 4, 5)], lambda a, b: a @ b)
spec("dot", lambda: [_std(5), _std(5)], np.dot)
spec("mv", lambda: [_std(3, 4), _std(4)], lambda a, b: a @ b)
spec("inner", lambda: [_std(3, 4), _std(5, 4)], np.inner)
spec("outer", lambda: [_std(3), _std(4)], np.outer)
spec("cross", lambda: [_std(4, 3), _std(4, 3)],
     lambda a, b: np.cross(a, b))
spec("kron", lambda: [_std(2, 3), _std(2, 2)], np.kron)
spec("t", lambda: [_std(3, 4)], np.transpose)
spec("trace", lambda: [_std(4, 4)], np.trace)
spec("diagonal", lambda: [_std(4, 4)], lambda x: np.diagonal(x))
spec("norm", lambda: [_std(3, 4)], lambda x: np.linalg.norm(x))
spec("dist", lambda: [_std(3, 4), _std(3, 4)],
     lambda a, b: np.linalg.norm(a - b))
spec("det", lambda: [_std(4, 4)], np.linalg.det)
spec("slogdet", lambda: [_std(4, 4)],
     lambda x: np.stack(np.linalg.slogdet(x)), grad=False)
spec("inverse", lambda: [_std(4, 4) + 4 * np.eye(4, dtype=np.float32)],
     np.linalg.inv)
spec("matrix_power", lambda: [_std(3, 3)],
     lambda x, n=3: np.linalg.matrix_power(x, 3), attrs={"n": 3})
spec("matrix_rank",
     lambda: [(_std(4, 2) @ _std(2, 4))],
     lambda x: np.linalg.matrix_rank(x), grad=False)
spec("multi_dot", lambda: [[_std(3, 4), _std(4, 5), _std(5, 2)]],
     lambda ms: np.linalg.multi_dot(ms), grad=False)
spec("cholesky",
     lambda: [(lambda a: (a @ a.T + 4 * np.eye(4)).astype(np.float32))(
         _std(4, 4))],
     np.linalg.cholesky)
spec("cholesky_solve",
     lambda: [_std(3, 1), np.linalg.cholesky(
         (lambda a: a @ a.T + 3 * np.eye(3))(_std(3, 3))).astype(
             np.float32)],
     lambda b, l: np.linalg.solve(l @ l.T, b), grad=False)
spec("solve",
     lambda: [_std(3, 3) + 3 * np.eye(3, dtype=np.float32), _std(3, 2)],
     np.linalg.solve)
spec("triangular_solve",
     lambda: [np.triu(_std(3, 3)) + 2 * np.eye(3, dtype=np.float32),
              _std(3, 2)],
     lambda a, b: scipy.linalg.solve_triangular(a, b, lower=False),
     grad=False)
spec("lstsq",
     lambda: [_std(5, 3), _std(5, 2)],
     lambda a, b: np.linalg.lstsq(a, b, rcond=None)[0], grad=False)
spec("pinv", lambda: [_std(4, 3)], np.linalg.pinv, grad=False)
spec("eigh",
     lambda: [(lambda a: ((a + a.T) / 2).astype(np.float32))(_std(4, 4))],
     lambda x: np.linalg.eigvalsh(x), grad=False)
spec("eigvalsh",
     lambda: [(lambda a: ((a + a.T) / 2).astype(np.float32))(_std(4, 4))],
     np.linalg.eigvalsh, grad=False)
spec("corrcoef", lambda: [_std(3, 8)], np.corrcoef, grad=False)
spec("cov", lambda: [_std(3, 8)], np.cov, grad=False)
spec("einsum",
     lambda: [_std(3, 4), _std(4, 5)],
     lambda a, b: np.einsum("ij,jk->ik", a, b),
     attrs={"_equation_first": "ij,jk->ik"})
spec("tensordot", lambda: [_std(3, 4), _std(4, 5)],
     lambda a, b, axes=1: np.tensordot(a, b, axes=1), attrs={"axes": 1})

# -- manipulation ------------------------------------------------------------
spec("reshape", lambda: [_std(3, 4)],
     lambda x, shape=(4, 3): x.reshape(4, 3), attrs={"shape": (4, 3)})
spec("transpose", lambda: [_std(3, 4, 5)],
     lambda x, perm=(2, 0, 1): x.transpose(2, 0, 1),
     attrs={"perm": (2, 0, 1)})
spec("swapaxes", lambda: [_std(3, 4, 5)],
     lambda x, axis0=0, axis1=2: x.swapaxes(0, 2),
     attrs={"axis0": 0, "axis1": 2})
spec("moveaxis", lambda: [_std(3, 4, 5)],
     lambda x, source=0, destination=2: np.moveaxis(x, 0, 2),
     attrs={"source": 0, "destination": 2})
spec("concat", lambda: [[_std(2, 3), _std(2, 3)]],
     lambda xs, axis=0: np.concatenate(xs, 0), attrs={"axis": 0},
     grad=False)
spec("stack", lambda: [[_std(2, 3), _std(2, 3)]],
     lambda xs, axis=0: np.stack(xs, 0), attrs={"axis": 0}, grad=False)
spec("split", lambda: [_std(4, 6)],
     lambda x, num_or_sections=2, axis=1: np.split(x, 2, 1)[0],
     attrs={"num_or_sections": 2, "axis": 1}, grad=False)
spec("chunk", lambda: [_std(4, 6)],
     lambda x, chunks=2, axis=1: np.split(x, 2, 1)[0],
     attrs={"chunks": 2, "axis": 1}, grad=False)
spec("unbind", lambda: [_std(3, 4)],
     lambda x, axis=0: x[0], attrs={"axis": 0}, grad=False)
spec("squeeze", lambda: [_std(3, 1, 4)],
     lambda x, axis=1: x.squeeze(1), attrs={"axis": 1})
spec("unsqueeze", lambda: [_std(3, 4)],
     lambda x, axis=1: x[:, None], attrs={"axis": 1})
spec("flatten", lambda: [_std(3, 4, 5)],
     lambda x, start_axis=1, stop_axis=2: x.reshape(3, -1),
     attrs={"start_axis": 1, "stop_axis": 2})
spec("flip", lambda: [_std(3, 4)], lambda x, axis=1: np.flip(x, 1),
     attrs={"axis": 1})
spec("rot90", lambda: [_std(3, 4)], lambda x: np.rot90(x))
spec("roll", lambda: [_std(3, 4)],
     lambda x, shifts=1, axis=1: np.roll(x, 1, 1),
     attrs={"shifts": 1, "axis": 1})
spec("tile", lambda: [_std(2, 3)],
     lambda x, repeat_times=(2, 2): np.tile(x, (2, 2)),
     attrs={"repeat_times": (2, 2)})
spec("expand", lambda: [_std(1, 4)],
     lambda x, shape=(3, 4): np.broadcast_to(x, (3, 4)),
     attrs={"shape": (3, 4)})
spec("broadcast_to", lambda: [_std(1, 4)],
     lambda x, shape=(3, 4): np.broadcast_to(x, (3, 4)),
     attrs={"shape": (3, 4)})
spec("expand_as", lambda: [_std(1, 4), _std(3, 4)],
     lambda x, y: np.broadcast_to(x, y.shape), grad=False)
spec("repeat_interleave", lambda: [_std(3, 4)],
     lambda x, repeats=2, axis=1: np.repeat(x, 2, 1),
     attrs={"repeats": 2, "axis": 1})
spec("gather", lambda: [_std(5, 4), _ints(5, 3)],
     lambda x, idx: x[idx], grad=False)
spec("gather_nd", lambda: [_std(4, 5), _ints(4, 3, 1)],
     lambda x, idx: x[idx[:, 0]], grad=False)
spec("index_select", lambda: [_std(5, 4), _ints(5, 3)],
     lambda x, idx, axis=0: x[idx], attrs={"axis": 0}, grad=False)
spec("take_along_axis", lambda: [_std(3, 5), _ints(5, 3, 2)],
     lambda x, idx, axis=1: np.take_along_axis(x, idx, 1),
     attrs={"axis": 1}, grad=False)
spec("put_along_axis", lambda: [_std(3, 5), _ints(5, 3, 1), _std(3, 1)],
     lambda x, idx, v, axis=1: np.put_along_axis(
         x.copy(), idx, v, 1) or np.put_along_axis(
             (y := x.copy()), idx, v, 1) or y,
     attrs={"axis": 1}, grad=False)
spec("index_add",
     lambda: [_std(5, 3), _ints(5, 2), 0, _std(2, 3)],
     lambda x, idx, axis, v: (lambda y: (np.add.at(y, idx, v), y)[1])(
         x.copy()),
     grad=False)
spec("masked_select", lambda: [np.arange(6, dtype=np.float32),
                               np.arange(6) % 2 == 0],
     lambda x, m: x[m], grad=False)
spec("masked_fill", lambda: [_std(3, 4), _bools(3, 4), np.float32(9.0)],
     lambda x, m, v: np.where(m, 9.0, x).astype(np.float32), grad=False)
spec("where", lambda: [_bools(3, 4), _std(3, 4), _std(3, 4)],
     np.where, grad=False)
spec("scatter",
     lambda: [_std(5, 3), _ints(5, 2), _std(2, 3)],
     lambda x, idx, v: (lambda y: (y.__setitem__(idx, v), y)[1])(x.copy()),
     grad=False)
spec("scatter_nd_add",
     lambda: [_std(5, 3), _ints(5, 2, 1), _std(2, 3)],
     lambda x, idx, v: (lambda y: (np.add.at(y, idx[:, 0], v), y)[1])(
         x.copy()),
     grad=False)
spec("pad", lambda: [_std(1, 2, 3, 4)],
     lambda x, pad=(1, 2, 0, 0): np.pad(
         x, ((0, 0), (0, 0), (0, 0), (1, 2))),
     attrs={"pad": (1, 2, 0, 0)}, grad=False)
spec("diff", lambda: [_std(3, 6)], lambda x: np.diff(x))
spec("crop", lambda: [_std(4, 5)],
     lambda x, shape=(2, 3), offsets=(1, 1): x[1:3, 1:4],
     attrs={"shape": (2, 3), "offsets": (1, 1)}, grad=False)
spec("slice", lambda: [_std(4, 5)],
     lambda x, axes=(0,), starts=(1,), ends=(3,): x[1:3],
     attrs={"axes": (0,), "starts": (1,), "ends": (3,)}, grad=False)
spec("strided_slice", lambda: [_std(6, 5)],
     lambda x, axes=(0,), starts=(0,), ends=(6,), strides=(2,): x[0:6:2],
     attrs={"axes": (0,), "starts": (0,), "ends": (6,), "strides": (2,)},
     grad=False)
spec("atleast_1d", lambda: [np.float32(3.0)],
     lambda x: np.atleast_1d(x), grad=False)
spec("atleast_2d", lambda: [_std(3)], np.atleast_2d, grad=False)
spec("atleast_3d", lambda: [_std(3, 4)], np.atleast_3d, grad=False)
spec("numel", lambda: [_std(3, 4)], lambda x: np.int64(12), grad=False)
spec("broadcast_tensors", lambda: [[_std(1, 4), _std(3, 1)]],
     lambda xs: np.broadcast_arrays(*xs)[0], grad=False)

# -- nn.functional (deterministic subset) -----------------------------------
spec("relu", lambda: [_std(3, 4)], lambda x: np.maximum(x, 0))
spec("relu6", lambda: [4 * _std(3, 4)],
     lambda x: np.clip(x, 0, 6))
spec("leaky_relu", lambda: [_std(3, 4)],
     lambda x: np.where(x >= 0, x, 0.01 * x))
spec("elu", lambda: [_std(3, 4)],
     lambda x: np.where(x > 0, x, np.expm1(x)))
spec("celu", lambda: [_std(3, 4)],
     lambda x: np.maximum(x, 0) + np.minimum(0, np.expm1(x)))
spec("selu", lambda: [_std(3, 4)],
     lambda x: 1.0507009873554805 * np.where(
         x > 0, x, 1.6732632423543772 * np.expm1(x)))
spec("gelu", lambda: [_std(3, 4)],
     lambda x: x * 0.5 * (1 + sps.erf(x / np.sqrt(2))))
spec("silu", lambda: [_std(3, 4)], lambda x: x / (1 + np.exp(-x)))
spec("swish", lambda: [_std(3, 4)], lambda x: x / (1 + np.exp(-x)))
spec("mish", lambda: [_std(3, 4)],
     lambda x: x * np.tanh(np.log1p(np.exp(x))))
spec("softplus", lambda: [_std(3, 4)], lambda x: np.log1p(np.exp(x)))
spec("softsign", lambda: [_std(3, 4)], lambda x: x / (1 + np.abs(x)))
spec("hardtanh", lambda: [2 * _std(3, 4)], lambda x: np.clip(x, -1, 1))
spec("hardsigmoid", lambda: [_std(3, 4)],
     lambda x: np.clip(x / 6 + 0.5, 0, 1))
spec("hardswish", lambda: [4 * _std(3, 4)],
     lambda x: x * np.clip(x + 3, 0, 6) / 6)
spec("hardshrink", lambda: [_std(3, 4)],
     lambda x: np.where(np.abs(x) > 0.5, x, 0))
spec("softshrink", lambda: [_std(3, 4)],
     lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0)))
spec("tanhshrink", lambda: [_std(3, 4)], lambda x: x - np.tanh(x))
spec("thresholded_relu", lambda: [_std(3, 4)],
     lambda x: np.where(x > 1.0, x, 0))
spec("log_sigmoid", lambda: [_std(3, 4)],
     lambda x: -np.log1p(np.exp(-x)))
spec("softmax", lambda: [_std(3, 4)],
     lambda x: sps.softmax(x, axis=-1))
spec("log_softmax", lambda: [_std(3, 4)],
     lambda x: sps.log_softmax(x, axis=-1))
spec("one_hot", lambda: [_ints(5, 6)],
     lambda x, num_classes=5: np.eye(5, dtype=np.float32)[x],
     attrs={"num_classes": 5}, grad=False)
spec("linear", lambda: [_std(3, 4), _std(4, 5), _std(5)],
     lambda x, w, b: x @ w + b)
spec("embedding", lambda: [_ints(6, 4), _std(6, 8)],
     lambda ids, w: w[ids], grad=False)
spec("normalize", lambda: [_std(3, 4)],
     lambda x: x / np.maximum(np.linalg.norm(x, axis=-1, keepdims=True),
                              1e-12))
spec("cosine_similarity", lambda: [_std(3, 8), _std(3, 8)],
     lambda a, b: (a * b).sum(-1) / (np.linalg.norm(a, axis=-1) *
                                     np.linalg.norm(b, axis=-1)))
spec("label_smooth", lambda: [np.eye(4, dtype=np.float32)],
     lambda x, epsilon=0.1: x * 0.9 + 0.1 / 4, attrs={"epsilon": 0.1})
spec("prelu", lambda: [_std(3, 4), np.float32([0.25])],
     lambda x, w: np.where(x >= 0, x, 0.25 * x))
spec("maxout", lambda: [_std(2, 4, 3)],
     lambda x, groups=2: x.reshape(2, 2, 2, 3).max(2),
     attrs={"groups": 2})
spec("glu", lambda: [_std(3, 8)],
     lambda x: x[:, :4] / (1 + np.exp(-x[:, 4:])))
spec("mse_loss", lambda: [_std(3, 4), _std(3, 4)],
     lambda a, b: ((a - b) ** 2).mean())
spec("l1_loss", lambda: [_std(3, 4), _std(3, 4)],
     lambda a, b: np.abs(a - b).mean())
spec("smooth_l1_loss", lambda: [_std(3, 4), _std(3, 4)],
     lambda a, b: np.where(np.abs(a - b) < 1.0,
                           0.5 * (a - b) ** 2,
                           np.abs(a - b) - 0.5).mean())
spec("kl_div", lambda: [np.log(sps.softmax(_std(3, 4), -1)),
                        sps.softmax(_std(3, 4), -1)],
     lambda lp, t, reduction="batchmean":
     (t * (np.log(t) - lp)).sum() / lp.shape[0],
     attrs={"reduction": "batchmean"})
spec("binary_cross_entropy",
     lambda: [sps.expit(_std(3, 4)).astype(np.float32),
              _bools(3, 4).astype(np.float32)],
     lambda p, y: (-(y * np.log(p) + (1 - y) * np.log(1 - p))).mean())
spec("binary_cross_entropy_with_logits",
     lambda: [_std(3, 4), _bools(3, 4).astype(np.float32)],
     lambda x, y: (np.maximum(x, 0) - x * y +
                   np.log1p(np.exp(-np.abs(x)))).mean())
spec("nll_loss",
     lambda: [sps.log_softmax(_std(4, 5), -1).astype(np.float32),
              _ints(5, 4)],
     lambda lp, y: -lp[np.arange(4), y].mean(), grad=False)
spec("cross_entropy", lambda: [_std(4, 5), _ints(5, 4)],
     lambda x, y: -sps.log_softmax(x, -1)[np.arange(4), y].mean(),
     grad=False)
spec("softmax_with_cross_entropy", lambda: [_std(4, 5), _ints(5, 4, 1)],
     lambda x, y: -sps.log_softmax(x, -1)[
         np.arange(4), y[:, 0]][:, None],
     grad=False)
spec("square_error_cost", lambda: [_std(3, 4), _std(3, 4)],
     lambda a, b: (a - b) ** 2)
spec("hinge_embedding_loss",
     lambda: [_std(3, 4),
              np.where(_bools(3, 4), 1.0, -1.0).astype(np.float32)],
     lambda x, y: np.where(y == 1, x, np.maximum(0, 1.0 - x)).mean(),
     grad=False)
spec("margin_ranking_loss",
     lambda: [_std(5), _std(5),
              np.where(_bools(5), 1.0, -1.0).astype(np.float32)],
     lambda a, b, y: np.maximum(0, -y * (a - b)).mean(), grad=False)
spec("cosine_embedding_loss",
     lambda: [_std(4, 6), _std(4, 6),
              np.where(_bools(4), 1.0, -1.0).astype(np.float32)],
     lambda a, b, y: np.where(
         y == 1,
         1 - (a * b).sum(-1) / (np.linalg.norm(a, axis=-1) *
                                np.linalg.norm(b, axis=-1)),
         np.maximum(0, (a * b).sum(-1) / (np.linalg.norm(a, axis=-1) *
                                          np.linalg.norm(b, axis=-1)))
     ).mean(), grad=False)
spec("pixel_shuffle", lambda: [_std(1, 4, 2, 2)],
     lambda x, upscale_factor=2: np.einsum(
         "bchwij->bhiwjc", x.reshape(1, 1, 2, 2, 2, 2).transpose(
             0, 1, 4, 5, 2, 3)).reshape(1, 1, 4, 4),
     attrs={"upscale_factor": 2}, grad=False)

# -- explicit exemptions ------------------------------------------------------
# Every yaml op NOT in SPECS must be justified here.
EXEMPT = {
    # random / generator ops: distributional tests live in
    # tests/test_aux.py + test_distributions_losses.py
    "bernoulli", "multinomial", "normal", "rand", "randint", "randn",
    "randperm", "uniform", "gumbel_softmax", "rrelu",
    # dropout family: stochastic; covered by test_functional_longtail +
    # layer tests
    "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    # creation/introspection without a numeric contract to diff
    "arange", "assign", "clone", "diag", "empty", "empty_like", "eye",
    "full", "full_like", "get_default_dtype", "linspace", "meshgrid",
    "ones", "ones_like", "to_tensor", "tril", "triu", "zeros",
    "zeros_like", "is_grad_enabled",
    # covered by dedicated suites (conv/pool/norm/attention/interp):
    # tests/test_components.py, test_models.py, test_functional_longtail
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose", "avg_pool1d", "avg_pool2d", "avg_pool3d",
    "max_pool1d", "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
    "adaptive_avg_pool2d", "adaptive_avg_pool3d", "adaptive_max_pool1d",
    "adaptive_max_pool2d", "adaptive_max_pool3d", "batch_norm",
    "layer_norm", "group_norm", "instance_norm", "local_response_norm",
    "rms_norm", "flash_attention", "scaled_dot_product_attention",
    "interpolate", "upsample", "fold", "unfold", "pixel_unshuffle",
    "channel_shuffle",
    # fused/Pallas kernels: covered by test_incubate, test_moe,
    # test_ring_attention, test_dropout_flash_ce (their yaml entries
    # exist to carry SPMD rules; see distributed/spmd_rules.py)
    "fused_linear", "fused_rms_norm", "fused_bias_act",
    "fused_layernorm_residual_dropout",
    "fused_rotary_position_embedding", "fused_softmax_ce_mean",
    "grouped_matmul", "moe_forward_indices",
    "flash_attention_segmented", "ring_attention",
    # composite losses covered in test_distributions_losses /
    # test_functional_longtail
    "ctc_loss", "gaussian_nll_loss", "poisson_nll_loss",
    "triplet_margin_loss", "multi_label_soft_margin_loss",
    "multi_margin_loss", "soft_margin_loss", "bilinear",
    # decompositions returning factor tuples (validated by reconstruction
    # in tests/test_extra_ops.py)
    "qr", "svd", "eig", "eigvals", "householder_product",
    # view/in-place aliases of covered ops
    "reshape_", "view", "as_strided", "multiply_", "shard_index",
    "scatter_nd", "index_put",
}


def _load_yaml_names():
    d = yaml.safe_load(open("paddle_tpu/ops/ops.yaml"))
    return [o["name"] for o in d["ops"]]


def _resolve(name):
    import paddle_tpu.nn.functional as F
    if hasattr(paddle, name):
        return getattr(paddle, name)
    if hasattr(F, name):
        return getattr(F, name)
    raise AttributeError(name)


def _wrap(v):
    if isinstance(v, list):
        return [_wrap(x) for x in v]
    if isinstance(v, np.ndarray) or isinstance(v, np.generic):
        if isinstance(v, np.generic) and not isinstance(v, np.floating):
            return v
        return paddle.to_tensor(np.asarray(v))
    return v


@pytest.mark.parametrize("name", sorted(SPECS))
def test_check_output(name):
    inputs_fn, attrs, ref, _ = SPECS[name]
    raw = inputs_fn()
    fn = _resolve(name)
    eq = attrs.pop("_equation_first", None)
    expect = np.asarray(ref(*[np.asarray(r, np.float32)
                              if isinstance(r, np.ndarray) and
                              np.issubdtype(r.dtype, np.floating) else r
                              for r in raw], **attrs))
    args = [_wrap(r) for r in raw]
    if eq is not None:
        got = fn(eq, *args, **attrs)
    else:
        got = fn(*args, **attrs)
    if isinstance(got, (tuple, list)):
        got = got[0]
    if eq is not None:
        attrs["_equation_first"] = eq
    np.testing.assert_allclose(
        np.asarray(got.numpy(), np.float32).reshape(expect.shape),
        expect.astype(np.float32), rtol=2e-4, atol=2e-5,
        err_msg=f"op {name} output mismatch vs NumPy oracle")


@pytest.mark.parametrize(
    "name", sorted(n for n, s in SPECS.items() if s[3]))
def test_check_grad(name):
    """Finite-difference gradient check (ref: op_test.py:3129): project
    onto a random cotangent and compare d<out,v>/dx at sampled positions
    against central differences."""
    inputs_fn, attrs, _, _ = SPECS[name]
    raw = inputs_fn()
    fn = _resolve(name)
    attrs = dict(attrs)
    eq = attrs.pop("_equation_first", None)
    diff_idx = [i for i, r in enumerate(raw)
                if isinstance(r, np.ndarray) and
                np.issubdtype(r.dtype, np.floating) and r.ndim > 0]
    if not diff_idx:
        pytest.skip("no differentiable inputs")
    rng = np.random.default_rng(7)

    def run(arrs):
        args = [_wrap(a) for a in arrs]
        out = fn(eq, *args, **attrs) if eq is not None else \
            fn(*args, **attrs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out

    out0 = run(raw)
    v = rng.standard_normal(out0.numpy().shape).astype(np.float32)

    # analytic
    tensors = [_wrap(a) for a in raw]
    for i in diff_idx:
        tensors[i].stop_gradient = False
    out = fn(eq, *tensors, **attrs) if eq is not None else \
        fn(*tensors, **attrs)
    if isinstance(out, (tuple, list)):
        out = out[0]
    s = (out * paddle.to_tensor(v)).sum()
    grads = paddle.grad(s, [tensors[i] for i in diff_idx],
                        allow_unused=True)

    eps = 1e-3
    for gi, i in enumerate(diff_idx):
        if grads[gi] is None:
            continue
        g = grads[gi].numpy()
        flat = raw[i].reshape(-1)
        for pos in rng.choice(flat.size, size=min(12, flat.size),
                              replace=False):
            orig = flat[pos]
            flat[pos] = orig + eps
            fp = float((run(raw).numpy() * v).sum())
            flat[pos] = orig - eps
            fm = float((run(raw).numpy() * v).sum())
            flat[pos] = orig
            numeric = (fp - fm) / (2 * eps)
            analytic = g.reshape(-1)[pos]
            assert abs(numeric - analytic) <= \
                5e-2 * max(1.0, abs(numeric), abs(analytic)), \
                (name, i, pos, analytic, numeric)


def test_fallback_parser_agrees_with_pyyaml():
    """The PyYAML-free fallback parser must produce the exact structure
    PyYAML does for ops.yaml AND for the scalar forms it historically
    mis-parsed (negatives, floats, exponents, quoted strings)."""
    from paddle_tpu.ops.op_registry import _parse_yaml_fallback
    text = open("paddle_tpu/ops/ops.yaml").read()
    assert _parse_yaml_fallback(text) == yaml.safe_load(text)["ops"]

    snippet = "\n".join([
        "ops:",
        "  - name: demo",
        "    module: math",
        "    nin: -1",
        "    scale: -2.5",
        "    eps: 1.5e-3",
        '    tag: "quoted: value"',
        "    alt: 'single quoted'",
        "    plain: a_string",
        "    vjp: false",
        "    fusable: true",
        "    fclass: reduce",     # marker classes stay plain strings
        # YAML 1.1 resolution corners where naive parsing diverges:
        "    notafloat: 1e5",      # no dot -> str in YAML 1.1
        "    wordbool: on",        # yes/no/on/off words are bools
        "    wordbool2: No",
        "    octal: 010",          # leading zero -> octal 8
        "    hexa: 0x1A",
        "    mixedcase: tRue",     # non-canonical casing stays str
        "    unsignedexp: 1.5e3",  # YAML 1.1 needs a signed exp -> str
        "    underscored: 1_000",
        "",
    ])
    assert _parse_yaml_fallback(snippet) == yaml.safe_load(snippet)["ops"]


def test_fusable_field_validation():
    """`fusable` is a CLASS marker — true (elementwise), `reduce`
    (reduction terminator), `epilogue` (contraction), `attention`
    (analysis-plane-only: planned through, never eagerly deferred) —
    with per-class structural constraints, a registered VJP (grads flow
    through the fused program's jax.vjp), and a registered fusion impl,
    so the YAML can't drift from the runtime."""
    import inspect

    from paddle_tpu.core import fusion
    from paddle_tpu.ops.op_registry import get_op_info

    d = yaml.safe_load(open("paddle_tpu/ops/ops.yaml"))["ops"]
    fusable = [o for o in d if o.get("fusable")]
    by_class = {}
    for o in fusable:
        assert o.get("fusable") in (True, "reduce", "epilogue",
                                    "attention"), \
            f"op {o['name']}: unknown fusable class {o.get('fusable')!r}"
        by_class.setdefault(o["fusable"], []).append(o)
    assert len(by_class.get(True, [])) >= 40   # elementwise families
    assert len(by_class.get("reduce", [])) >= 8
    assert len(by_class.get("epilogue", [])) >= 2
    # the attention family (ROADMAP item-3 step-one residue): exactly
    # the three kernel entry points, q/k/v(+seg) arity, and the eager
    # fusion DAG must NEVER defer them — try_fuse rejects the class
    attn = by_class.get("attention", [])
    assert sorted(o["name"] for o in attn) == [
        "flash_attention", "flash_attention_segmented",
        "ring_attention"]
    for o in attn:
        assert int(o["nin"]) in (3, 4), \
            f"attention-fusable {o['name']} has nin={o['nin']}"
        assert fusion.try_fuse(o["name"], lambda *a: None, (), {},
                               attrs=()) is None
    for o in fusable:
        name = o["name"]
        assert o.get("vjp", True) is True, \
            f"fusable op {name} lacks a VJP (vjp: false)"
        assert not o.get("variadic", False), \
            f"fusable op {name} is variadic — not a fusable arity"
        info = get_op_info(name)
        assert info is not None and info.get("has_vjp"), name
    for o in by_class.get(True, []):
        assert 1 <= int(o["nin"]) <= 2, \
            f"elementwise-fusable {o['name']} has nin={o['nin']}"
        assert int(o["nargs"]) <= 3, \
            f"elementwise-fusable {o['name']} has nargs={o['nargs']}"
    # reductions: single-tensor ops whose Python wrapper exposes the
    # axis/keepdim reduction signature (squared_l2_norm is a fixed full
    # reduction by contract) and whose parametric impl is registered
    _FIXED_REDUCTIONS = {"squared_l2_norm"}
    import paddle_tpu.nn.functional as F
    for o in by_class.get("reduce", []):
        name = o["name"]
        assert int(o["nin"]) == 1, \
            f"reduce-fusable {name} must take one tensor (nin=1)"
        assert name in fusion._PIMPLS, \
            f"reduce-fusable {name} has no parametric impl registered"
        if name not in _FIXED_REDUCTIONS:
            fn = getattr(paddle, name, None) or getattr(F, name)
            params = inspect.signature(fn).parameters
            assert "axis" in params and "keepdim" in params, \
                f"reduce-fusable {name} lacks the axis/keepdim surface"
    # contractions: two-or-more tensor ops with a registered parametric
    # impl (matmul's transpose flags / linear's optional bias)
    for o in by_class.get("epilogue", []):
        name = o["name"]
        assert int(o["nin"]) == 2, \
            f"epilogue-fusable {name} must be a binary contraction"
        assert name in fusion._PIMPLS, \
            f"epilogue-fusable {name} has no parametric impl registered"
    # every fusable name that wins its OP_TABLE slot has a registered
    # canonical impl so the fused program can be rebuilt from its name
    from paddle_tpu.ops.op_registry import OP_TABLE
    for name in {o["name"] for o in by_class.get(True, [])}:
        if OP_TABLE[name].get("fusable"):
            assert name in fusion._IMPLS or name in fusion._PIMPLS, \
                f"fusable op {name} has no fusion impl registered"
    # the registry normalizes/validates the class marker at load time
    from paddle_tpu.ops.op_registry import _norm_fusable
    with pytest.raises(ValueError):
        _norm_fusable("demo", "reduction")  # typo'd class must not load


def test_shape_spec_coverage_and_golden_run():
    """PTC005 coverage contract (ISSUE 7): every op marked `fusable:`
    carries a `shape:` spec, no non-fusable op does (both directions,
    the PTL005 pattern), and every declared spec agrees with the LIVE
    fusion impl on sample avals — the golden run the capture planner's
    abstract interpreter stands on."""
    from paddle_tpu.analysis import shapes
    from paddle_tpu.ops.op_registry import (OP_TABLE, SHAPE_SPECS,
                                            _norm_shape_spec)

    d = yaml.safe_load(open("paddle_tpu/ops/ops.yaml"))["ops"]
    for o in d:
        if o.get("fusable"):
            assert o.get("shape") in SHAPE_SPECS, \
                (f"fusable op {o['name']} lacks a valid `shape:` spec "
                 f"(got {o.get('shape')!r})")
        else:
            assert o.get("shape") is None, \
                f"non-fusable op {o['name']} declares a shape spec"
    # the loaded table mirrors the yaml (load-time validation ran)
    fusable_names = {o["name"] for o in d if o.get("fusable")}
    for name in fusable_names:
        assert OP_TABLE[name]["shape_spec"] in SHAPE_SPECS
    # golden run: abstract spec == live impl on sample avals, all ops.
    # The attention entry points register their aval impls at their
    # (lazily imported) definition sites — import them first so their
    # validation is NON-vacuous (infer_output_aval would otherwise
    # return None and skip the grading)
    import paddle_tpu.distributed.ring_attention  # noqa: F401
    import paddle_tpu.ops.pallas.flash_attention  # noqa: F401
    from paddle_tpu.core import fusion
    for name in ("flash_attention", "flash_attention_segmented",
                 "ring_attention"):
        assert name in fusion._PIMPLS, \
            f"{name} registered no aval impl — its spec would grade " \
            f"vacuously"
    diags = shapes.validate_specs()
    assert diags == [], "\n".join(x.render() for x in diags)
    # the attention detector detects too: a deliberately wrong spec
    # over the real impl must fail its golden run
    assert any(x.rule == "PTC005"
               for x in shapes.validate_op("flash_attention",
                                           "elementwise"))
    # the detector detects: a wrong spec must fail the golden run...
    assert any(x.rule == "PTC005"
               for x in shapes.validate_op("mean", "broadcast"))
    # ...and load-time validation rejects unknown/missing specs
    with pytest.raises(ValueError):
        _norm_shape_spec("demo", "reduceish", True)
    with pytest.raises(ValueError):
        _norm_shape_spec("demo", None, "reduce")


def test_yaml_fully_covered():
    names = set(_load_yaml_names())
    covered = set(SPECS) | EXEMPT
    uncovered = sorted(names - covered)
    assert uncovered == [], f"yaml ops lacking spec/exemption: {uncovered}"
    assert len(SPECS) >= 150, len(SPECS)
    # exemptions must not rot: every exempt name still exists in yaml
    stale = sorted(EXEMPT - names)
    assert stale == [], f"stale exemptions: {stale}"
