"""Flight recorder: ring semantics, dump triggers (explicit /
unhandled exception / watchdog timeout), the per-request serving
lifecycle trail, crash forensics for a kill-point mid-decode, and the
gauge-vs-journal consistency contract (ISSUE 8)."""
from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability import flight
from paddle_tpu.serving import GenerationServer
from paddle_tpu.utils import fault_injection as fi


@pytest.fixture()
def dump_dir(tmp_path):
    """Route dumps into the test's tmp dir; restore afterwards."""
    prev = paddle.get_flags("FLAGS_flight_dump_dir")
    paddle.set_flags({"FLAGS_flight_dump_dir": str(tmp_path)})
    try:
        yield str(tmp_path)
    finally:
        paddle.set_flags(prev)


@pytest.fixture()
def quiet_thread_hook():
    """Install the crash hooks with the default traceback print
    silenced (the crashes below are seeded); uninstall afterwards."""
    prev = threading.excepthook
    threading.excepthook = lambda args: None
    flight.install_crash_hooks()
    try:
        yield
    finally:
        flight.uninstall_crash_hooks()
        threading.excepthook = prev


class FakeEngine:
    """Duck-typed decode engine (test_observability.py pattern): enough
    surface for GenerationServer's host orchestration, no jax."""

    def __init__(self, slots=2, step_sleep=0.0):
        self.max_slots = slots
        self.max_seq = 64
        self.eos_id = None
        self.step_sleep = step_sleep
        self.pos = np.zeros(slots, np.int32)
        self.active = np.zeros(slots, bool)

    def prefill(self, slot, ids):
        self.pos[slot] = len(ids)
        self.active[slot] = True
        return 7

    def step(self):
        if self.step_sleep:
            time.sleep(self.step_sleep)
        out = np.zeros(self.max_slots, np.int64)
        for s in range(self.max_slots):
            if self.active[s]:
                self.pos[s] += 1
                out[s] = 100 + s
        return out

    def release(self, slot):
        self.active[slot] = False
        self.pos[slot] = 0


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

class TestRing:
    def test_record_and_fields(self):
        flight.clear()
        flight.record("t", "ev", trace_id="abc", n=3)
        (e,) = flight.events(category="t")
        assert e["cat"] == "t" and e["name"] == "ev"
        assert e["trace_id"] == "abc" and e["attrs"] == {"n": 3}
        assert e["thread"] == threading.current_thread().name
        assert e["ts_us"] > 0

    def test_kill_switch(self):
        flight.clear()
        paddle.set_flags({"FLAGS_flight_recorder": 0})
        try:
            flight.record("t", "dropped")
            assert flight.events(category="t") == []
        finally:
            paddle.set_flags({"FLAGS_flight_recorder": 1})
        flight.record("t", "kept")
        assert [e["name"] for e in flight.events(category="t")] == ["kept"]

    def test_capacity_eviction_and_dropped(self):
        prev = paddle.get_flags("FLAGS_flight_recorder_capacity")
        try:
            paddle.set_flags({"FLAGS_flight_recorder_capacity": 32})
            flight.clear()
            for i in range(100):
                flight.record("t", "e", i=i)
            evs = flight.events(category="t")
            assert len(evs) == 32
            # the LAST 32 survive (a black box keeps the newest tail)
            assert [e["attrs"]["i"] for e in evs] == list(range(68, 100))
            assert flight.dropped() == 100 - 32
            assert flight.appended() == 100
        finally:
            paddle.set_flags(prev)
            flight.clear()

    def test_trace_and_last_n_filters(self):
        flight.clear()
        for i in range(6):
            flight.record("t", "e", trace_id=f"r{i % 2}", i=i)
        r0 = flight.events(trace_id="r0")
        assert [e["attrs"]["i"] for e in r0] == [0, 2, 4]
        assert len(flight.events(n=2, category="t")) == 2

    def test_chrome_events_shape(self):
        flight.clear()
        flight.record("t", "mark", trace_id="x", k=1)
        ev = next(e for e in flight.chrome_events()
                  if e["name"] == "t.mark")
        assert ev["ph"] == "i"
        assert ev["args"] == {"k": 1, "trace_id": "x"}


# ---------------------------------------------------------------------------
# dumps
# ---------------------------------------------------------------------------

class TestDump:
    def test_explicit_dump_roundtrip(self, dump_dir):
        flight.clear()
        flight.record("t", "one", trace_id="tr", a=1)
        flight.record("t", "two")
        before = obs.default_registry().get(
            "observability.dumps_total").value(trigger="explicit")
        path = flight.dump(trigger="explicit", note="unit")
        assert path.startswith(dump_dir)
        assert flight.last_dump_path() == path
        header, evs = flight.load_dump(path)
        assert header["kind"] == "flight_header"
        assert header["trigger"] == "explicit"
        assert header["note"] == "unit"
        assert header["events"] == len(evs)
        names = [e["name"] for e in evs if e["cat"] == "t"]
        assert names == ["one", "two"]
        tr = [e for e in evs if e.get("trace_id") == "tr"]
        assert tr and tr[0]["attrs"] == {"a": 1}
        after = obs.default_registry().get(
            "observability.dumps_total").value(trigger="explicit")
        assert after == before + 1
        # every line of the dump is standalone JSON (forensics greppable)
        with open(path) as f:
            for line in f:
                json.loads(line)
        # rendering never crashes and names the trigger
        text = flight.render_events(evs, header)
        assert "trigger=explicit" in text and "t.one" in text

    def test_dump_works_with_recorder_off(self, dump_dir):
        flight.clear()
        flight.record("t", "pre")
        paddle.set_flags({"FLAGS_flight_recorder": 0})
        try:
            _, evs = flight.load_dump(flight.dump())
        finally:
            paddle.set_flags({"FLAGS_flight_recorder": 1})
        assert any(e["name"] == "pre" for e in evs)

    def test_find_dumps_newest_first(self, dump_dir):
        p1 = flight.dump(trigger="explicit")
        time.sleep(0.02)
        p2 = flight.dump(trigger="explicit")
        found = flight.find_dumps(dump_dir)
        assert found[0] == p2 and p1 in found

    def test_cli_renders_dump(self, dump_dir, capsys):
        flight.clear()
        flight.record("cli", "seeded", trace_id="cli-1")
        flight.record("cli", "other", trace_id="cli-2")
        path = flight.dump()
        from paddle_tpu.observability.__main__ import main
        assert main(["--flight", path]) == 0
        out = capsys.readouterr().out
        assert "cli.seeded" in out and "[cli-1]" in out
        # --trace filters to one request's trail
        assert main(["--flight", path, "--trace", "cli-1",
                     "--json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert {e["trace_id"] for e in data["events"]} == {"cli-1"}


# ---------------------------------------------------------------------------
# crash triggers
# ---------------------------------------------------------------------------

class TestCrashHooks:
    def test_thread_crash_dumps(self, dump_dir, quiet_thread_hook):
        flight.clear()
        flight.record("t", "before_crash", probe=7)

        def boom():
            raise RuntimeError("seeded thread crash")

        t = threading.Thread(target=boom)
        t.start()
        t.join()
        dumps = flight.find_dumps(dump_dir)
        assert dumps, "thread crash left no flight dump"
        header, evs = flight.load_dump(dumps[0])
        assert header["trigger"] == "exception"
        assert any(e["name"] == "before_crash" for e in evs)
        crash = [e for e in evs if e["cat"] == "crash"]
        assert crash and crash[-1]["attrs"]["error"] == "RuntimeError"

    def test_sys_excepthook_wrapper_dumps(self, dump_dir,
                                          quiet_thread_hook):
        import sys
        flight.clear()
        flight.record("t", "mainline_state")
        try:
            raise ValueError("seeded main-thread crash")
        except ValueError:
            tp, val, tb = sys.exc_info()
        prev_sys = sys.__excepthook__  # silence the chained print
        try:
            sys.__excepthook__ = lambda *a: None
            # call the installed wrapper directly (raising through the
            # real top-level would kill pytest); chaining is part of
            # the contract and must not raise
            sys.excepthook(tp, val, tb)
        finally:
            sys.__excepthook__ = prev_sys
        dumps = flight.find_dumps(dump_dir)
        assert dumps
        header, evs = flight.load_dump(dumps[0])
        assert header["trigger"] == "exception"
        assert any(e["name"] == "mainline_state" for e in evs)

    def test_uninstall_restores_hooks(self):
        import sys
        prev_sys, prev_thr = sys.excepthook, threading.excepthook
        flight.install_crash_hooks()
        assert sys.excepthook is not prev_sys
        flight.uninstall_crash_hooks()
        assert sys.excepthook is prev_sys
        assert threading.excepthook is prev_thr


class TestWatchdogDump:
    def test_timeout_leaves_forensics(self, dump_dir):
        """A hung step doesn't just bump timeouts_total: it freezes the
        black box (ISSUE 8 satellite: hung collective -> forensics)."""
        from paddle_tpu.distributed.watchdog import (Watchdog,
                                                     WatchdogTimeout)
        flight.clear()
        flight.record("t", "pre_hang_state")
        before = obs.default_registry().get(
            "observability.dumps_total").value(trigger="watchdog")
        release = threading.Event()
        wd = Watchdog(timeout=0.2)
        with pytest.raises(WatchdogTimeout):
            wd.run(release.wait, 30.0)
        release.set()  # unblock the worker thread
        after = obs.default_registry().get(
            "observability.dumps_total").value(trigger="watchdog")
        assert after == before + 1
        dumps = flight.find_dumps(dump_dir)
        assert dumps
        header, evs = flight.load_dump(dumps[0])
        assert header["trigger"] == "watchdog"
        wd_evs = [e for e in evs if e["cat"] == "watchdog"]
        assert wd_evs and wd_evs[-1]["name"] == "timeout"
        assert any(e["name"] == "pre_hang_state" for e in evs)


class TestSelfCheckIntegration:
    def test_flight_self_check_robust_to_live_env(self):
        """report.self_check must pass with production crash hooks
        already installed AND the operator's recorder kill switch off —
        and must take its synthetic crash back out of the ring."""
        import signal

        from paddle_tpu.analysis.report import self_check
        prev_flag = paddle.get_flags("FLAGS_flight_recorder")
        prev_thr = threading.excepthook
        # the documented production setup, incl. a live-dump signal
        flight.install_crash_hooks(signals=(signal.SIGUSR1,))
        try:
            paddle.set_flags({"FLAGS_flight_recorder": 0})
            out = self_check()
            assert out["checks"]["flight"] is True, out["detail"]
            # the operator's kill-switch choice survives the check
            assert not paddle.get_flags(
                "FLAGS_flight_recorder")["FLAGS_flight_recorder"]
            # production hooks are back in place, state consistent
            assert flight._hooks_installed
            # the SIGUSR1 live-dump trigger survives too (SIG_DFL for
            # SIGUSR1 would TERMINATE the process on the next signal)
            assert signal.getsignal(signal.SIGUSR1) \
                is not signal.SIG_DFL
            assert signal.SIGUSR1 in flight._prev_signals
            # no synthetic residue pollutes later REAL dumps
            assert flight.events(category="selfcheck") == []
            assert not any(
                "self-check seeded" in str(e.get("attrs", {}))
                for e in flight.events(category="crash"))
        finally:
            paddle.set_flags(prev_flag)
            flight.uninstall_crash_hooks()
            threading.excepthook = prev_thr


class TestChromeMerge:
    def test_flight_events_land_in_chrome_export(self, tmp_path):
        """export_chrome_tracing carries all three planes: spans,
        step-timeline counters, and the flight trail as instant marks."""
        from paddle_tpu import profiler
        if profiler._lib is None:
            pytest.skip("native tracer unavailable")
        flight.clear()
        flight.record("merge", "probe", trace_id="m-1", k=2)
        path = str(tmp_path / "trace.json")
        profiler.export_chrome_tracing(path)
        with open(path) as f:
            data = json.load(f)
        marks = [e for e in data.get("traceEvents", [])
                 if e.get("name") == "merge.probe"]
        assert marks, "flight event missing from the merged trace"
        assert marks[0]["ph"] == "i"
        assert marks[0]["args"] == {"k": 2, "trace_id": "m-1"}


# ---------------------------------------------------------------------------
# serving lifecycle trail
# ---------------------------------------------------------------------------

class TestServingLifecycle:
    def test_full_trail_in_order(self):
        flight.clear()
        q0 = obs.default_registry().get(
            "serving.queue_seconds").value()["count"]
        d0 = obs.default_registry().get(
            "serving.decode_seconds").value()["count"]
        srv = GenerationServer(FakeEngine())
        try:
            req = srv.submit([1, 2, 3], max_new_tokens=3)
            assert req["done"].wait(30)
            trail = srv.trace(req)  # req dict and trace_id both work
            assert trail == srv.trace(req["trace_id"])
            names = [e["name"] for e in trail]
            assert names[:3] == ["submit", "queued", "admitted"]
            assert names[-1] == "finished"
            assert names[3:-1] == ["decode"] * (len(names) - 4)
            assert trail[-1]["attrs"]["tokens"] == 3
            # decode steps carry a monotone token count
            toks = [e["attrs"]["tokens"] for e in trail
                    if e["name"] == "decode"]
            assert toks == sorted(toks)
            # latency split landed: one queue + one decode observation
            assert obs.default_registry().get(
                "serving.queue_seconds").value()["count"] == q0 + 1
            assert obs.default_registry().get(
                "serving.decode_seconds").value()["count"] == d0 + 1
        finally:
            srv.shutdown()

    def test_rejected_submission_is_journaled(self):
        flight.clear()
        srv = GenerationServer(FakeEngine())
        srv.shutdown()
        with pytest.raises(RuntimeError):
            srv.submit([1], 2)
        evs = flight.events(category="serving")
        assert evs[-1]["name"] == "rejected"
        assert evs[-1]["attrs"]["reason"] == "shutting_down"

    def test_expired_request_is_journaled(self):
        flight.clear()
        q_hist = obs.default_registry().get("serving.queue_seconds")
        q0 = q_hist.value()["count"]
        srv = GenerationServer(FakeEngine(slots=1, step_sleep=0.02))
        try:
            blocker = srv.submit([1, 2], 500)
            starved = srv.submit([3], 8, deadline=0.15)
            assert starved["done"].wait(30)
            assert isinstance(starved["error"], TimeoutError)
            trail = srv.trace(starved)
            assert trail[-1]["name"] == "expired"
            assert trail[-1]["attrs"]["error"] == "TimeoutError"
            # no survivorship bias: the starved (never-admitted) request
            # lands in queue_seconds too — its whole life was queue
            # time — alongside the blocker's admission observation
            assert q_hist.value()["count"] >= q0 + 2
            blocker["expires"] = time.monotonic()  # let shutdown drain
        finally:
            srv.shutdown(timeout=30)

    def test_gauges_agree_with_journal_under_submit_shutdown(self):
        """Concurrent submit + drain shutdown: the queue/in-flight
        gauges must read 0 afterwards and the journal must account for
        every submitted request with exactly one terminal event."""
        flight.clear()
        srv = GenerationServer(FakeEngine(slots=2, step_sleep=0.002))
        reqs, rejected = [], 0
        lock = threading.Lock()

        def submitter(k):
            nonlocal rejected
            for i in range(5):
                try:
                    r = srv.submit([k, i], max_new_tokens=3)
                    with lock:
                        reqs.append(r)
                except RuntimeError:
                    with lock:
                        rejected += 1
                time.sleep(0.001)

        threads = [threading.Thread(target=submitter, args=(k,))
                   for k in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.01)
        assert srv.shutdown(drain=True, timeout=60)
        for t in threads:
            t.join(timeout=30)
        # every accepted request ran to completion (drain contract)
        for r in reqs:
            assert r["done"].is_set()
            assert r["error"] is None
        g = obs.default_registry()
        assert g.get("serving.queue_depth").value() == 0
        assert g.get("serving.in_flight").value() == 0
        # journal cross-check: one terminal event per accepted request,
        # one rejected event per refused submission
        evs = flight.events(category="serving")
        by_name = {}
        for e in evs:
            by_name.setdefault(e["name"], []).append(e)
        finished = {e["trace_id"] for e in by_name.get("finished", ())}
        assert finished == {r["trace_id"] for r in reqs}
        assert len(by_name.get("rejected", ())) == rejected
        # admitted counter agrees with the journal
        assert srv.admitted == len(by_name.get("admitted", ()))


# ---------------------------------------------------------------------------
# crash forensics: kill-point mid-decode (the acceptance scenario)
# ---------------------------------------------------------------------------

class TestKillPointForensics:
    def test_decode_crash_dump_carries_victim_lifecycle(
            self, dump_dir, quiet_thread_hook):
        """PR 2's KillPoint harness poisons a decode step; the server
        loop thread dies as a real preemption would and the automatic
        exception dump must contain the victim request's COMPLETE
        lifecycle trail under its trace_id."""
        flight.clear()
        srv = GenerationServer(FakeEngine(slots=1))
        victim = None
        try:
            # let two decode passages through, kill the third: the
            # victim is mid-decode with tokens already produced
            fi.inject("serving.decode", kill=True, skip=2)
            victim = srv.submit([1, 2, 3], max_new_tokens=50)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline \
                    and not flight.find_dumps(dump_dir):
                time.sleep(0.01)
            dumps = flight.find_dumps(dump_dir)
            assert dumps, "kill-point crash left no flight dump"
            header, evs = flight.load_dump(dumps[0])
            assert header["trigger"] == "exception"
            tid = victim["trace_id"]
            trail = [e for e in evs if e.get("trace_id") == tid]
            names = [e["name"] for e in trail]
            assert names[:3] == ["submit", "queued", "admitted"]
            assert "decode" in names  # tokens were flowing when it died
            # no terminal event: the request died mid-flight
            assert not ({"finished", "expired", "failed"} & set(names))
            # the crash itself is the journal's closing entry
            crash = [e for e in evs if e["cat"] == "crash"]
            assert crash and crash[-1]["attrs"]["error"] == "KillPoint"
            # the victim never completed
            assert not victim["done"].is_set()
        finally:
            fi.clear("serving.decode")
            srv.shutdown(drain=False, timeout=0.5)

    def test_loop_survives_plain_exception_and_journals_it(self):
        """A non-kill injected fault fails the in-flight requests but
        the loop survives — and the journal says why."""
        flight.clear()
        srv = GenerationServer(FakeEngine(slots=1))
        try:
            fi.inject("serving.decode", times=1)
            req = srv.submit([1, 2], max_new_tokens=5)
            assert req["done"].wait(30)
            assert isinstance(req["error"], fi.InjectedFault)
            trail = srv.trace(req)
            assert trail[-1]["name"] == "failed"
            assert any(e["name"] == "loop_error"
                       for e in flight.events(category="serving"))
            # the loop is still alive: a fresh request serves
            out = srv.generate([5], max_new_tokens=2, timeout=30)
            assert len(out) == 2
        finally:
            fi.clear("serving.decode")
            srv.shutdown()
