"""Multi-controller execution of the FRAMEWORK'S OWN distributed stack.

The reference's distributed tests run the *product API* across real
processes — worker scripts call paddle.distributed / DistTensor APIs
under real NCCL (ref: test/collective/test_communication_api_base.py:
58-79 shells the launcher on per-API worker scripts;
test/auto_parallel/hybrid_strategy/semi_auto_llama.py trains a sharded
Llama through the user-facing API with save/load). The sibling
test_multicontroller.py proves the *runtime* spans processes; this file
proves the *product* does: every worker below imports only paddle_tpu —
no raw jax calls — and exercises shard_llama + DistTrainStep +
shard_batch + dist checkpoint save/load + the comm watchdog across real
processes, asserted against single-controller oracles.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(tmp_path, script_body, nproc=2, env=None, name="worker"):
    script = tmp_path / f"{name}.py"
    script.write_text(textwrap.dedent(script_body))
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--log_dir", str(tmp_path / f"log_{name}"),
           "--nproc_per_node", str(nproc), str(script)]
    e = dict(os.environ, PYTHONPATH=_REPO_ROOT, JAX_PLATFORMS="cpu")
    # the conftest's 8-virtual-device XLA_FLAGS must NOT leak into the
    # workers: each controller owns exactly its own devices
    e.pop("XLA_FLAGS", None)
    if env:
        e.update(env)
    return (subprocess.run(cmd, capture_output=True, text=True, timeout=300,
                           env=e, cwd=_REPO_ROOT),
            tmp_path / f"log_{name}")


# Tiny Llama config shared verbatim by the workers and the in-process
# oracle — any drift would invalidate the acc-align comparison.
CFG = ("dict(vocab_size=64, hidden_size=32, intermediate_size=64, "
       "num_hidden_layers=2, num_attention_heads=4, "
       "num_key_value_heads=2, use_flash_attention=False)")

# Deterministic global batch, identical in workers and oracle.
BATCH = ("np.random.default_rng(7).integers(0, 64, (4, 16))"
         ".astype(np.int32)")


def _oracle_losses(n_steps, lr=1e-3):
    """Single-controller training of the identical model/batch — the
    acc-align contract (ref: hybrid_strategy llama tests assert sharded
    loss == single-card loss)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.dist_train import DistTrainStep
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         LlamaPretrainingCriterion)
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(**eval(CFG)))
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=m.parameters())
    crit = LlamaPretrainingCriterion()
    step = DistTrainStep(m, lambda lg, lb: crit(lg, lb), opt)
    ids = eval(BATCH)
    return [float(step(ids, ids)) for _ in range(n_steps)]


FRAMEWORK_PRELUDE = f"""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    r, n = dist.get_rank(), dist.get_world_size()

    from paddle_tpu.distributed import ProcessMesh, shard_batch
    from paddle_tpu.distributed.dist_train import DistTrainStep
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         LlamaPretrainingCriterion,
                                         shard_llama)

    def build_sharded(seed, lr=1e-3):
        paddle.seed(seed)
        m = LlamaForCausalLM(LlamaConfig.tiny(**{CFG}))
        mesh = ProcessMesh(np.arange(n), dim_names=["fsdp"])
        shard_llama(m, mesh, tp_axis=None, fsdp_axis="fsdp")
        opt = paddle.optimizer.AdamW(learning_rate=lr,
                                     parameters=m.parameters())
        crit = LlamaPretrainingCriterion()
        step = DistTrainStep(m, lambda lg, lb: crit(lg, lb), opt)
        return m, step, mesh

    ids_g = {BATCH}
    rows = ids_g.shape[0] // n
    local = ids_g[r * rows:(r + 1) * rows]   # THIS process's shard only
"""


class TestFrameworkStackMultiController:
    def test_shard_llama_dist_train_matches_single_controller(self,
                                                              tmp_path):
        """ZeRO-3 Llama training through shard_llama + DistTrainStep +
        shard_batch on a global mesh spanning 2 processes, each feeding
        only its host-local batch rows; losses must match the
        single-controller oracle."""
        proc, log = _run_launch(tmp_path, FRAMEWORK_PRELUDE + """
    m, step, mesh = build_sharded(seed=0)
    losses = []
    for _ in range(3):
        ids = shard_batch(local, mesh)       # local rows -> global batch
        losses.append(float(step(ids, ids)))
    print("MC_FW_LOSSES", " ".join(f"{l:.6f}" for l in losses))
        """)
        assert proc.returncode == 0, proc.stderr + proc.stdout
        oracle = _oracle_losses(3)
        for i in range(2):
            body = (log / f"workerlog.{i}").read_text()
            assert "MC_FW_LOSSES" in body, body
            got = [float(x) for x in
                   body.split("MC_FW_LOSSES")[1].split()[:3]]
            np.testing.assert_allclose(got, oracle, rtol=2e-4)

    def test_sharded_checkpoint_across_process_counts(self, tmp_path):
        """dist.save_state_dict from 2 processes, load_state_dict into 4
        — reshard-on-load across a CHANGED process count, params AND
        optimizer state, with the resumed loss matching the
        uninterrupted single-controller oracle (ref:
        distributed/checkpoint/save_state_dict.py:145 multi-rank writes
        + semi_auto_parallel_checkpoint_dedup_tensor.py)."""
        ckpt = tmp_path / "ckpt"
        env = {"MC_CKPT": str(ckpt)}
        save, save_log = _run_launch(tmp_path, FRAMEWORK_PRELUDE + """
    import os
    m, step, mesh = build_sharded(seed=0)
    ids = shard_batch(local, mesh)
    l0 = float(step(ids, ids))
    dist.save_state_dict({"model": m.state_dict(),
                          "opt": step.state_dict()},
                         os.environ["MC_CKPT"])
    print("MC_CKPT_SAVE_LOSS", f"{l0:.6f}")
        """, nproc=2, env=env, name="saver")
        assert save.returncode == 0, save.stderr + save.stdout
        assert (ckpt / "metadata.json").exists()

        resume, resume_log = _run_launch(tmp_path, FRAMEWORK_PRELUDE + """
    import os
    # deliberately DIFFERENT init: every weight must come from the load
    m, step, mesh = build_sharded(seed=123)
    opt_sd = step.state_dict()
    dist.load_state_dict({"model": m.state_dict(), "opt": opt_sd},
                         os.environ["MC_CKPT"])
    step.set_state_dict(opt_sd)
    ids = shard_batch(local, mesh)
    l1 = float(step(ids, ids))
    print("MC_CKPT_RESUME_LOSS", f"{l1:.6f}")
        """, nproc=4, env=env, name="resumer")
        assert resume.returncode == 0, resume.stderr + resume.stdout

        oracle = _oracle_losses(2)
        saved = (save_log / "workerlog.0").read_text()
        l0 = float(saved.split("MC_CKPT_SAVE_LOSS")[1].split()[0])
        np.testing.assert_allclose([l0], [oracle[0]], rtol=2e-4)
        for i in range(4):
            body = (resume_log / f"workerlog.{i}").read_text()
            assert "MC_CKPT_RESUME_LOSS" in body, body
            l1 = float(body.split("MC_CKPT_RESUME_LOSS")[1].split()[0])
            np.testing.assert_allclose([l1], [oracle[1]], rtol=2e-4)

    def test_worker_death_watchdog_names_collective(self, tmp_path):
        """Failure path (ref: comm_task_manager.h:37 — the watchdog
        exists to NAME the collective a dead peer left hanging): rank 1
        dies mid-step; rank 0, blocked in all_reduce, gets the hang
        attributed by the watchdog monitor; the launcher detects the
        death and tears the job down with a nonzero exit."""
        proc, log = _run_launch(tmp_path, """
    import os
    import time
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.watchdog import install_watchdog

    dist.init_parallel_env()
    r = dist.get_rank()
    install_watchdog(timeout=3.0)
    # both ranks meet once so the ring is actually up
    dist.barrier()
    print("MC_RING_UP", r, flush=True)
    if r == 1:
        time.sleep(8)
        os._exit(3)          # die mid-step, skipping the collective
    t = paddle.to_tensor(np.ones((4,), np.float32))
    dist.all_reduce(t)       # blocks forever on the dead peer
    print("MC_SHOULD_NOT_REACH", r)
        """, nproc=2)
        assert proc.returncode != 0, proc.stdout + proc.stderr
        assert "failed with exit code 3" in proc.stderr, proc.stderr
        rank0 = (log / "workerlog.0").read_text()
        assert "MC_RING_UP 0" in rank0, rank0
        assert "MC_SHOULD_NOT_REACH" not in rank0, rank0
        # the watchdog names the hanging collective before teardown
        assert "[watchdog]" in rank0, rank0
        assert "all_reduce" in rank0, rank0
