"""SPMD rule table tests (ref: paddle/phi/infermeta/spmd_rules/ + its
registry): every ops.yaml `spmd:` name resolves to a real rule, rule
propagation semantics match the reference's InferSpmd contracts, and the
custom-kernel shard_map appliers produce exactly the collectives the
rules imply (HLO-inspected on the 8-virtual-device CPU mesh)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed import spmd_rules as R
from paddle_tpu.ops.op_registry import OP_TABLE


def _mesh(shape, names):
    devs = np.asarray(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


class TestRuleTable:
    def test_every_yaml_rule_exists(self):
        named = {info["spmd_rule"] for info in OP_TABLE.values()
                 if info.get("spmd_rule")}
        assert len(named) >= 10
        for rule in sorted(named):
            assert callable(R.get_rule(rule)), rule

    def test_at_least_20_ops_carry_rules(self):
        ops = [n for n, info in OP_TABLE.items() if info.get("spmd_rule")]
        assert len(ops) >= 20, ops
        # the custom kernels MUST be covered (VERDICT item 8)
        for required in ("flash_attention", "grouped_matmul",
                         "moe_forward_indices", "matmul", "embedding"):
            assert OP_TABLE[required]["spmd_rule"], required

    def test_unknown_rule_raises(self):
        with pytest.raises(KeyError, match="GSPMD"):
            R.get_rule("definitely_not_a_rule")


class TestRuleSemantics:
    def test_matmul_passthrough_and_contraction(self):
        _, out = R.get_rule("matmul")(P("dp", None), P(None, "mp"))
        assert tuple(out) == ("dp", "mp")
        # contraction sharded on both sides (=> partial/psum) is legal
        _, out = R.get_rule("matmul")(P(None, "mp"), P("mp", None))
        assert tuple(out) == (None, None)
        with pytest.raises(ValueError, match="contraction"):
            R.get_rule("matmul")(P(None, "dp"), P("mp", None))

    def test_reduction_drops_reduced_dim(self):
        _, out = R.get_rule("reduction")(P("dp", "mp"), axis=1)
        assert tuple(out) == ("dp",)
        _, out = R.get_rule("reduction")(P("dp", "mp"), axis=1,
                                         keepdims=True)
        assert tuple(out) == ("dp", None)

    def test_softmax_rejects_sharded_axis(self):
        with pytest.raises(ValueError, match="softmax"):
            R.get_rule("softmax")(P(None, "mp"))
        _, out = R.get_rule("softmax")(P("dp", None))
        assert tuple(out) == ("dp", None)

    def test_layer_norm_rejects_sharded_feature(self):
        with pytest.raises(ValueError):
            R.get_rule("layer_norm")(P("dp", None, "mp"))
        _, out = R.get_rule("layer_norm")(P("dp", "sp", None))
        assert tuple(out) == ("dp", "sp", None)

    def test_embedding_row_shard_rejected(self):
        with pytest.raises(ValueError, match="VocabParallel"):
            R.get_rule("embedding")(P("dp", None), P("mp", None))
        _, out = R.get_rule("embedding")(P("dp", None), P(None, "mp"))
        assert tuple(out) == ("dp", None, "mp")

    def test_flash_attention_seq_shard_redirects_to_ring(self):
        spec = P("dp", None, "mp", None)
        _, out = R.get_rule("flash_attention")(spec, spec, spec)
        assert tuple(out) == ("dp", None, "mp", None)
        bad = P(None, "sp", None, None)
        with pytest.raises(ValueError, match="ring_attention"):
            R.get_rule("flash_attention")(bad, bad, bad)

    def test_grouped_matmul_expert_and_token_conflict(self):
        with pytest.raises(ValueError, match="dispatch"):
            R.get_rule("grouped_matmul")(P("dp", None),
                                         P("ep", None, None))
        _, out = R.get_rule("grouped_matmul")(P("dp", None),
                                              P(None, None, None))
        assert tuple(out) == ("dp", None)

    def test_conv_spatial_and_channel_shard_rejected(self):
        w = P(None, None, None, None)
        # NCHW (default): dims 2,3 spatial; dim 1 input-channel
        with pytest.raises(ValueError, match="halo"):
            R.get_rule("conv")(P(None, None, "dp", None), w)
        with pytest.raises(ValueError, match="channel"):
            R.get_rule("conv")(P(None, "mp", None, None), w)
        # NHWC: dims 1,2 spatial; dim 3 input-channel
        with pytest.raises(ValueError, match="halo"):
            R.get_rule("conv")(P(None, "dp", None, None), w,
                               data_format="NHWC")
        with pytest.raises(ValueError, match="channel"):
            R.get_rule("conv")(P(None, None, None, "mp"), w,
                               data_format="NHWC")
        _, out = R.get_rule("conv")(P("dp", None, None, None), w)
        assert tuple(out) == ("dp", None, None, None)

    def test_matmul_batch_dim_merge_and_conflict(self):
        _, out = R.get_rule("matmul")(P(None, None, None),
                                      P("dp", None, None))
        assert tuple(out) == ("dp", None, None)
        with pytest.raises(ValueError, match="batch"):
            R.get_rule("matmul")(P("dp", None, None),
                                 P("mp", None, None))
        with pytest.raises(ValueError, match="rank"):
            R.get_rule("matmul")(P("dp"), P(None, None))


def _collectives(hlo_text):
    names = ("all-gather", "all-reduce", "all-to-all",
             "collective-permute", "reduce-scatter")
    return [n for n in names if n in hlo_text]


class TestShardMapAppliers:
    """HLO inspection: the decomposition each rule promises is the one
    the compiled program has (the reference asserts its rules through
    reshard-insertion tests, test/auto_parallel/reshard_*)."""

    def test_flash_attention_batch_head_sharded_no_collectives(self):
        mesh = _mesh((2, 4), ("dp", "mp"))
        rng = np.random.default_rng(0)
        B, L, H, D = 4, 32, 8, 16
        q = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(
            np.float32))
        k = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(
            np.float32))
        v = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(
            np.float32))
        sh = NamedSharding(mesh, P("dp", None, "mp", None))
        qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))

        def f(q_, k_, v_):
            return R.shard_map_flash_attention(
                mesh, q_, k_, v_, batch_axis="dp", head_axis="mp",
                causal=True)

        lowered = jax.jit(f).lower(qs, ks, vs).compile()
        hlo = lowered.as_text()
        assert _collectives(hlo) == [], _collectives(hlo)
        # numerics match the unsharded oracle
        from paddle_tpu.ops.pallas.flash_attention import _sdpa_xla
        out = jax.jit(f)(qs, ks, vs)
        ref = _sdpa_xla(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_grouped_matmul_token_sharded_no_collectives(self):
        mesh = _mesh((8,), ("dp",))
        rng = np.random.default_rng(1)
        T, K, N, E = 64, 16, 24, 4
        lhs = jnp.asarray(rng.standard_normal((T, K)).astype(np.float32))
        rhs = jnp.asarray(rng.standard_normal((E, K, N)).astype(
            np.float32))
        # per-shard group sizes: each shard's 8 rows split 2 per expert
        gs = jnp.asarray([2, 2, 2, 2], jnp.int32)

        def f(l_, r_, g_):
            return R.shard_map_grouped_matmul(mesh, l_, r_, g_,
                                              token_axis="dp")

        ls = jax.device_put(lhs, NamedSharding(mesh, P("dp", None)))
        lowered = jax.jit(f).lower(ls, rhs, gs).compile()
        assert _collectives(lowered.as_text()) == []

    def test_moe_dispatch_expert_sharded_has_alltoall_or_gather(self):
        mesh = _mesh((8,), ("ep",))
        rng = np.random.default_rng(2)
        E, C, H, F, T = 8, 16, 32, 64, 128
        tokens = jnp.asarray(rng.standard_normal((T, H)).astype(
            np.float32))
        gw = jnp.asarray(rng.standard_normal((H, E)).astype(np.float32))
        wi = jnp.asarray(rng.standard_normal((E, H, F)).astype(
            np.float32))
        wo = jnp.asarray(rng.standard_normal((E, F, H)).astype(
            np.float32))

        def f(tk, wi_, wo_):
            out = R.shard_map_moe_dispatch(
                mesh, tk, gw, wi_, wo_, top_k=2, capacity=C,
                act=jax.nn.gelu, ep_axis="ep")
            return out[0] if isinstance(out, tuple) else out

        with mesh:
            lowered = jax.jit(f).lower(tokens, wi, wo).compile()
        hlo = lowered.as_text()
        cols = _collectives(hlo)
        # expert-sharded FFN: tokens must move to their expert's shard
        assert cols, "expected resharding collectives, found none"
        # ...and NOT by all-gathering the full expert weights (that
        # would defeat expert parallelism's memory saving): no
        # all-gather may produce a full [E,H,F]/[E,F,H] weight tensor
        import re as _re
        for m in _re.finditer(r"all-gather[^=]*=\s*\w+\[([\d,]+)\]", hlo):
            shape = tuple(int(x) for x in m.group(1).split(","))
            assert sorted(shape) != sorted((E, H, F)), \
                f"full expert weights all-gathered: {shape}"
