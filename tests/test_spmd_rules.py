"""SPMD rule table tests (ref: paddle/phi/infermeta/spmd_rules/ + its
registry): every ops.yaml `spmd:` name resolves to a real rule, rule
propagation semantics match the reference's InferSpmd contracts, and the
custom-kernel shard_map appliers produce exactly the collectives the
rules imply (HLO-inspected on the 8-virtual-device CPU mesh)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed import spmd_rules as R
from paddle_tpu.ops.op_registry import OP_TABLE


def _mesh(shape, names):
    devs = np.asarray(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


class TestRuleTable:
    def test_every_yaml_rule_exists(self):
        named = {info["spmd_rule"] for info in OP_TABLE.values()
                 if info.get("spmd_rule")}
        assert len(named) >= 10
        for rule in sorted(named):
            assert callable(R.get_rule(rule)), rule

    def test_at_least_20_ops_carry_rules(self):
        ops = [n for n, info in OP_TABLE.items() if info.get("spmd_rule")]
        assert len(ops) >= 20, ops
        # the custom kernels MUST be covered (VERDICT item 8)
        for required in ("flash_attention", "grouped_matmul",
                         "moe_forward_indices", "matmul", "embedding"):
            assert OP_TABLE[required]["spmd_rule"], required

    def test_unknown_rule_raises(self):
        with pytest.raises(KeyError, match="GSPMD"):
            R.get_rule("definitely_not_a_rule")


class TestRuleSemantics:
    def test_matmul_passthrough_and_contraction(self):
        _, out = R.get_rule("matmul")(P("dp", None), P(None, "mp"))
        assert tuple(out) == ("dp", "mp")
        # contraction sharded on both sides (=> partial/psum) is legal
        _, out = R.get_rule("matmul")(P(None, "mp"), P("mp", None))
        assert tuple(out) == (None, None)
        with pytest.raises(ValueError, match="contraction"):
            R.get_rule("matmul")(P(None, "dp"), P("mp", None))

    def test_reduction_drops_reduced_dim(self):
        _, out = R.get_rule("reduction")(P("dp", "mp"), axis=1)
        assert tuple(out) == ("dp",)
        _, out = R.get_rule("reduction")(P("dp", "mp"), axis=1,
                                         keepdims=True)
        assert tuple(out) == ("dp", None)

    def test_softmax_rejects_sharded_axis(self):
        with pytest.raises(ValueError, match="softmax"):
            R.get_rule("softmax")(P(None, "mp"))
        _, out = R.get_rule("softmax")(P("dp", None))
        assert tuple(out) == ("dp", None)

    def test_layer_norm_rejects_sharded_feature(self):
        with pytest.raises(ValueError):
            R.get_rule("layer_norm")(P("dp", None, "mp"))
        _, out = R.get_rule("layer_norm")(P("dp", "sp", None))
        assert tuple(out) == ("dp", "sp", None)

    def test_embedding_row_shard_rejected(self):
        with pytest.raises(ValueError, match="VocabParallel"):
            R.get_rule("embedding")(P("dp", None), P("mp", None))
        _, out = R.get_rule("embedding")(P("dp", None), P(None, "mp"))
        assert tuple(out) == ("dp", None, "mp")

    def test_flash_attention_seq_shard_redirects_to_ring(self):
        spec = P("dp", None, "mp", None)
        _, out = R.get_rule("flash_attention")(spec, spec, spec)
        assert tuple(out) == ("dp", None, "mp", None)
        bad = P(None, "sp", None, None)
        with pytest.raises(ValueError, match="ring_attention"):
            R.get_rule("flash_attention")(bad, bad, bad)

    def test_grouped_matmul_expert_and_token_conflict(self):
        with pytest.raises(ValueError, match="dispatch"):
            R.get_rule("grouped_matmul")(P("dp", None),
                                         P("ep", None, None))
        _, out = R.get_rule("grouped_matmul")(P("dp", None),
                                              P(None, None, None))
        assert tuple(out) == ("dp", None)

    def test_conv_spatial_and_channel_shard_rejected(self):
        w = P(None, None, None, None)
        # NCHW (default): dims 2,3 spatial; dim 1 input-channel
        with pytest.raises(ValueError, match="halo"):
            R.get_rule("conv")(P(None, None, "dp", None), w)
        with pytest.raises(ValueError, match="channel"):
            R.get_rule("conv")(P(None, "mp", None, None), w)
        # NHWC: dims 1,2 spatial; dim 3 input-channel
        with pytest.raises(ValueError, match="halo"):
            R.get_rule("conv")(P(None, "dp", None, None), w,
                               data_format="NHWC")
        with pytest.raises(ValueError, match="channel"):
            R.get_rule("conv")(P(None, None, None, "mp"), w,
                               data_format="NHWC")
        _, out = R.get_rule("conv")(P("dp", None, None, None), w)
        assert tuple(out) == ("dp", None, None, None)

    def test_matmul_batch_dim_merge_and_conflict(self):
        _, out = R.get_rule("matmul")(P(None, None, None),
                                      P("dp", None, None))
        assert tuple(out) == ("dp", None, None)
        with pytest.raises(ValueError, match="batch"):
            R.get_rule("matmul")(P("dp", None, None),
                                 P("mp", None, None))
        with pytest.raises(ValueError, match="rank"):
            R.get_rule("matmul")(P("dp"), P(None, None))


def _collectives(hlo_text):
    names = ("all-gather", "all-reduce", "all-to-all",
             "collective-permute", "reduce-scatter")
    return [n for n in names if n in hlo_text]


class TestShardMapAppliers:
    """HLO inspection: the decomposition each rule promises is the one
    the compiled program has (the reference asserts its rules through
    reshard-insertion tests, test/auto_parallel/reshard_*)."""

    def test_flash_attention_batch_head_sharded_no_collectives(self):
        mesh = _mesh((2, 4), ("dp", "mp"))
        rng = np.random.default_rng(0)
        B, L, H, D = 4, 32, 8, 16
        q = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(
            np.float32))
        k = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(
            np.float32))
        v = jnp.asarray(rng.standard_normal((B, L, H, D)).astype(
            np.float32))
        sh = NamedSharding(mesh, P("dp", None, "mp", None))
        qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))

        def f(q_, k_, v_):
            return R.shard_map_flash_attention(
                mesh, q_, k_, v_, batch_axis="dp", head_axis="mp",
                causal=True)

        lowered = jax.jit(f).lower(qs, ks, vs).compile()
        hlo = lowered.as_text()
        assert _collectives(hlo) == [], _collectives(hlo)
        # numerics match the unsharded oracle
        from paddle_tpu.ops.pallas.flash_attention import _sdpa_xla
        out = jax.jit(f)(qs, ks, vs)
        ref = _sdpa_xla(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_grouped_matmul_token_sharded_no_collectives(self):
        mesh = _mesh((8,), ("dp",))
        rng = np.random.default_rng(1)
        T, K, N, E = 64, 16, 24, 4
        lhs = jnp.asarray(rng.standard_normal((T, K)).astype(np.float32))
        rhs = jnp.asarray(rng.standard_normal((E, K, N)).astype(
            np.float32))
        # per-shard group sizes: each shard's 8 rows split 2 per expert
        gs = jnp.asarray([2, 2, 2, 2], jnp.int32)

        def f(l_, r_, g_):
            return R.shard_map_grouped_matmul(mesh, l_, r_, g_,
                                              token_axis="dp")

        ls = jax.device_put(lhs, NamedSharding(mesh, P("dp", None)))
        lowered = jax.jit(f).lower(ls, rhs, gs).compile()
        assert _collectives(lowered.as_text()) == []

    def test_moe_dispatch_expert_sharded_has_alltoall_or_gather(self):
        mesh = _mesh((8,), ("ep",))
        rng = np.random.default_rng(2)
        E, C, H, F, T = 8, 16, 32, 64, 128
        tokens = jnp.asarray(rng.standard_normal((T, H)).astype(
            np.float32))
        gw = jnp.asarray(rng.standard_normal((H, E)).astype(np.float32))
        wi = jnp.asarray(rng.standard_normal((E, H, F)).astype(
            np.float32))
        wo = jnp.asarray(rng.standard_normal((E, F, H)).astype(
            np.float32))

        def f(tk, wi_, wo_):
            out = R.shard_map_moe_dispatch(
                mesh, tk, gw, wi_, wo_, top_k=2, capacity=C,
                act=jax.nn.gelu, ep_axis="ep")
            return out[0] if isinstance(out, tuple) else out

        with mesh:
            lowered = jax.jit(f).lower(tokens, wi, wo).compile()
        hlo = lowered.as_text()
        cols = _collectives(hlo)
        # expert-sharded FFN: tokens must move to their expert's shard
        assert cols, "expected resharding collectives, found none"
        # ...and NOT by all-gathering the full expert weights (that
        # would defeat expert parallelism's memory saving): no
        # all-gather may produce a full [E,H,F]/[E,F,H] weight tensor
        import re as _re
        for m in _re.finditer(r"all-gather[^=]*=\s*\w+\[([\d,]+)\]", hlo):
            shape = tuple(int(x) for x in m.group(1).split(","))
            assert sorted(shape) != sorted((E, H, F)), \
                f"full expert weights all-gathered: {shape}"


class TestExpandedRuleTable:
    """Round-5 rule-breadth parity (VERDICT r4 #2): the reference ships
    ~50 explicit per-op rules (paddle/phi/infermeta/spmd_rules/); the
    table must match that breadth so propagation never silently
    replicates an input GSPMD can't see through."""

    def test_rule_count_reaches_reference_parity(self):
        assert len(R.list_rules()) >= 50, len(R.list_rules())

    def test_at_least_60_ops_carry_rules(self):
        ops = [n for n, info in OP_TABLE.items() if info.get("spmd_rule")]
        assert len(ops) >= 60, (len(ops), ops)

    # -- indexing family --
    def test_gather_axis_sharded_table_rejected(self):
        with pytest.raises(ValueError, match="masked-gather"):
            R.get_rule("gather")(P("mp", None), P("dp"), axis=0)
        _, out = R.get_rule("gather")(P(None, "mp"), P("dp"), axis=0)
        assert tuple(out) == ("dp", "mp")

    def test_gather_nd_reshards_indexed_dims(self):
        (fx, _), out = R.get_rule("gather_nd")(
            P("mp", None), P("dp", None), index_depth=1)
        assert tuple(fx) == (None, None)       # indexed dim forced whole
        assert tuple(out) == ("dp", None)      # index batch + x trailing

    def test_scatter_written_dim_and_updates_forced_whole(self):
        (fx, fidx, fupd), out = R.get_rule("scatter")(
            P("dp", "mp"), P("dp"), P("dp", "mp"), axis=0)
        assert tuple(fx) == (None, "mp")
        assert tuple(out) == (None, "mp")
        # every shard holds the full written axis, so it must see ALL
        # writes: index and the updates' axis dim reshard whole
        assert tuple(fidx) == (None,)
        assert tuple(fupd) == (None, "mp")

    def test_take_along_axis_and_one_hot(self):
        (fx, _), out = R.get_rule("take_along_axis")(
            P("dp", "mp"), P("dp", None), axis=1)
        assert tuple(fx) == ("dp", None)
        assert tuple(out) == ("dp", None)  # output == index sharding
        # an axis-sharded INDEX is legal: each shard computes its slice
        _, out = R.get_rule("take_along_axis")(P(None), P("dp"), axis=0)
        assert tuple(out) == ("dp",)
        _, out = R.get_rule("one_hot")(P("dp"))
        assert tuple(out) == ("dp", None)

    # -- shape family --
    def test_slice_pad_roll_drop_touched_dims(self):
        for rule in ("slice", "pad", "roll"):
            kw = {"axes": (1,)} if rule != "pad" else {"padded_dims": (1,)}
            (fx,), out = R.get_rule(rule)(P("dp", "mp", None), **kw)
            assert tuple(fx) == ("dp", None, None), rule
            assert tuple(out) == ("dp", None, None), rule

    def test_stack_unsqueeze_insert_unsharded_dim(self):
        _, out = R.get_rule("stack")(P("dp", None), P("dp", None), axis=1)
        assert tuple(out) == ("dp", None, None)
        _, out = R.get_rule("unsqueeze")(P("dp", "mp"), axis=0)
        assert tuple(out) == (None, "dp", "mp")

    def test_squeeze_drops_dim(self):
        _, out = R.get_rule("squeeze")(P("dp", None, "mp"), axis=1)
        assert tuple(out) == ("dp", "mp")

    def test_flatten_keeps_leading_sharding_iff_inner_whole(self):
        (fx,), out = R.get_rule("flatten")(P("dp", None, "mp"),
                                           start_axis=0, stop_axis=1)
        assert tuple(out) == ("dp", "mp")
        (fx,), out = R.get_rule("flatten")(P("dp", "mp", None),
                                           start_axis=0, stop_axis=1)
        assert tuple(out) == (None, None)      # inner sharded: replicate
        assert tuple(fx) == (None, None, None)

    def test_tile_and_expand_as(self):
        (fx,), out = R.get_rule("tile")(P("dp", "mp"), repeats=(1, 2))
        assert tuple(out) == ("dp", None)
        # short repeats align to TRAILING dims (numpy semantics)
        (fx,), out = R.get_rule("tile")(P("dp", "mp"), repeats=(2,))
        assert tuple(out) == ("dp", None)
        (fx,), out = R.get_rule("tile")(P("dp", "mp"), repeats=(3, 1, 1))
        assert tuple(out) == (None, "dp", "mp")
        _, out = R.get_rule("expand_as")(P("dp", None),
                                         P(None, None, "mp"))
        assert tuple(out) == (None, "dp", "mp")

    def test_unbind_drops_axis(self):
        (fx,), out = R.get_rule("unbind")(P("dp", "mp"), axis=0)
        assert tuple(fx) == (None, "mp")
        assert tuple(out) == ("mp",)

    def test_cast_triu_where_add_n_passthrough(self):
        _, out = R.get_rule("cast")(P("dp", "mp"))
        assert tuple(out) == ("dp", "mp")
        _, out = R.get_rule("triu")(P("dp", None, None))
        assert tuple(out) == ("dp", None, None)
        _, out = R.get_rule("where")(P("dp", None), P("dp", None),
                                     P(None, None))
        assert tuple(out) == ("dp", None)
        _, out = R.get_rule("add_n")(P("dp", None), P("dp", None))
        assert tuple(out) == ("dp", None)

    # -- scan / norm family --
    def test_cumsum_axis_forced_whole(self):
        (fx,), out = R.get_rule("cumsum")(P("dp", "mp"), axis=1)
        assert tuple(fx) == ("dp", None)
        assert tuple(out) == ("dp", None)

    def test_topk_argsort_axis_forced_whole(self):
        (fx,), (vals, idx) = R.get_rule("topk")(P("dp", "mp"), axis=1)
        assert tuple(fx) == ("dp", None)
        assert tuple(vals) == ("dp", None) and tuple(idx) == ("dp", None)
        (fx,), out = R.get_rule("argsort")(P("dp", "mp"), axis=-1)
        assert tuple(fx) == ("dp", None)

    def test_norm_family_reduction_shaped(self):
        _, out = R.get_rule("p_norm")(P("dp", "mp"), axis=1)
        assert tuple(out) == ("dp",)
        _, out = R.get_rule("logsumexp")(P("dp", "mp"), axis=0)
        assert tuple(out) == ("mp",)
        # the grad-clip hot path: ANY sharding reduces to a replicated
        # scalar without gathering the parameter
        _, out = R.get_rule("squared_l2_norm")(P("fsdp", "mp"))
        assert tuple(out) == ()

    def test_normalize_and_glu_axis_forced_whole(self):
        (fx,), out = R.get_rule("normalize")(P("dp", "mp"), axis=1)
        assert tuple(fx) == ("dp", None)
        assert tuple(out) == ("dp", None)
        (fx,), out = R.get_rule("glu")(P("dp", "mp"), axis=-1)
        assert tuple(fx) == ("dp", None)

    def test_gather_negative_axis_normalized(self):
        _, out = R.get_rule("gather")(P("dp", None), P("mp"), axis=-1)
        assert tuple(out) == ("dp", "mp")

    def test_swiglu_packed_vs_paired(self):
        _, out = R.get_rule("swiglu")(P("dp", "mp"), P("dp", "mp"))
        assert tuple(out) == ("dp", "mp")      # tp paired form passes
        with pytest.raises(ValueError, match="packed"):
            R.get_rule("swiglu")(P("dp", "mp"))

    def test_class_sharded_softmax_ce(self):
        _, out = R.get_rule("c_softmax_with_cross_entropy")(
            P("dp", "mp"), P("dp"))
        assert tuple(out) == ("dp",)           # class dim legally sharded

    def test_moe_combine_inverse_of_dispatch(self):
        _, out = R.get_rule("moe_combine")(P("ep", None))
        assert tuple(out) == ("ep", None)


class TestGatherAvoidsGspmdReplicate:
    """The reason the reference has these rules at all: propagation
    alone can silently replicate an input and eat the memory/ICI win.
    A batch-sharded gather driven by the rule's specs runs with ZERO
    collectives and a still-sharded output (no full-replicate)."""

    def test_sharded_gather_zero_collectives(self):
        mesh = _mesh((8,), ("dp",))
        rng = np.random.default_rng(0)
        table = jnp.asarray(rng.standard_normal((64, 32)).astype(
            np.float32))
        ids_np = rng.integers(0, 64, (32,)).astype(np.int32)

        in_specs, out_spec = R.get_rule("gather")(P(None, None), P("dp"),
                                                  axis=0)

        def local(t_, i_):
            return jnp.take(t_, i_, axis=0)

        from paddle_tpu.distributed._mesh_axes import shard_map
        f = jax.jit(shard_map(local, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_spec, check_vma=False))
        tr = jax.device_put(table, NamedSharding(mesh, P(None, None)))
        ids = jax.device_put(jnp.asarray(ids_np),
                             NamedSharding(mesh, P("dp")))
        hlo = f.lower(tr, ids).compile().as_text()
        for col in ("all-gather", "all-reduce", "all-to-all",
                    "collective-permute"):
            assert col not in hlo, col
        out = f(tr, ids)
        # output stays dp-sharded: each device holds 1/8 of the rows
        # (jax trims trailing Nones from specs; compare normalized)
        assert tuple(out.sharding.spec) == tuple(out_spec)[:1]
        assert out.addressable_shards[0].data.shape[0] == 4
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(table)[ids_np])
