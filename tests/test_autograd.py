"""Eager autograd: backward, grad accumulation, hooks, PyLayer,
higher-order. Numeric gradients are checked against finite differences,
mirroring the reference's OpTest.check_grad (ref: test/legacy_test/
op_test.py:3129)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def numeric_grad(f, x, eps=1e-3):
    g = np.zeros_like(x)
    flat_x = x.reshape(-1)
    flat_g = g.reshape(-1)
    for i in range(flat_x.size):
        orig = flat_x[i]
        flat_x[i] = orig + eps
        hi = f(x.copy().reshape(x.shape))
        flat_x[i] = orig - eps
        lo = f(x.copy().reshape(x.shape))
        flat_x[i] = orig
        flat_g[i] = (hi - lo) / (2 * eps)
    return g


class TestBackward:
    def test_simple_chain(self):
        x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])

    def test_grad_accumulation(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])
        x.clear_grad()
        assert x.grad is None

    def test_diamond_graph(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        a = x * 3
        b = x * 4
        y = a * b  # y = 12 x^2, dy/dx = 24x = 48
        y.backward()
        np.testing.assert_allclose(x.grad.item(), 48.0)

    def test_shared_input_multi_consumer(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x.exp()
        z = (y + y * y).sum()  # dz/dy = 1 + 2y; dz/dx = (1+2e^x)e^x
        z.backward()
        e = np.exp([1.0, 2.0])
        np.testing.assert_allclose(x.grad.numpy(), (1 + 2 * e) * e, rtol=1e-5)

    def test_stop_gradient_blocks(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = paddle.to_tensor([2.0])  # stop_gradient=True
        z = (x * y).sum()
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])
        assert y.grad is None

    def test_detach_cuts_graph(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = (x * 2).detach()
        z = (y * 3).sum()
        z.backward()
        assert x.grad is None

    def test_no_grad_context(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient

    def test_non_scalar_backward_with_grad(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * x
        y.backward(paddle.to_tensor([1.0, 0.5]))
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])

    def test_non_scalar_backward_raises(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        with pytest.raises(RuntimeError):
            (x * x).backward()

    def test_matmul_grad_numeric(self, rng):
        a_np = rng.standard_normal((3, 4)).astype(np.float32)
        b_np = rng.standard_normal((4, 2)).astype(np.float32)
        a = paddle.to_tensor(a_np, stop_gradient=False)
        b = paddle.to_tensor(b_np, stop_gradient=False)
        loss = paddle.matmul(a, b).sum()
        loss.backward()
        ng = numeric_grad(lambda x: (x @ b_np).sum(), a_np.copy())
        np.testing.assert_allclose(a.grad.numpy(), ng, rtol=1e-2, atol=1e-2)
        ng_b = numeric_grad(lambda y: (a_np @ y).sum(), b_np.copy())
        np.testing.assert_allclose(b.grad.numpy(), ng_b, rtol=1e-2, atol=1e-2)

    def test_broadcast_grad(self):
        x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
        b = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
        ((x + b) ** 2).sum().backward()
        np.testing.assert_allclose(b.grad.numpy(),
                                   2 * (np.array([[2, 3], [4, 5]])).sum(0))

    def test_multi_output_grad(self):
        x = paddle.to_tensor([3.0, 1.0, 2.0], stop_gradient=False)
        vals, idx = paddle.topk(x, 2)
        vals.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0, 1.0])

    def test_getitem_grad(self):
        x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
        (x[1] * 5).backward()
        np.testing.assert_allclose(x.grad.numpy(), [0, 5, 0])

    def test_cast_grad(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        x.astype("bfloat16").astype("float32").sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0])

    def test_backward_twice_same_graph(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.item(), 8.0)


class TestFunctionalGrad:
    def test_paddle_grad(self):
        x = paddle.to_tensor(3.0, stop_gradient=False)
        y = x ** 2
        (g,) = paddle.grad(y, x)
        assert g.item() == pytest.approx(6.0)
        assert x.grad is None  # functional API does not write .grad

    def test_grad_multiple_inputs(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = paddle.to_tensor(3.0, stop_gradient=False)
        z = x * y + x
        gx, gy = paddle.grad(z, [x, y])
        assert gx.item() == pytest.approx(4.0)
        assert gy.item() == pytest.approx(2.0)

    def test_allow_unused(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = paddle.to_tensor(3.0, stop_gradient=False)
        z = x * 2
        with pytest.raises(RuntimeError):
            paddle.grad(z, [x, y])
        gx, gy = paddle.grad(z, [x, y], allow_unused=True)
        assert gy is None

    def test_hooks(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        seen = []
        h = x.register_hook(lambda g: seen.append(g.numpy()))
        (x * 2).sum().backward()
        assert len(seen) == 1
        np.testing.assert_allclose(seen[0], [2.0])
        h.remove()
        x.clear_grad()
        (x * 2).sum().backward()
        assert len(seen) == 1

    def test_hook_modifies_grad(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        x.register_hook(lambda g: g * 10)
        (x * 2).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [20.0])


class TestPyLayer:
    def test_custom_forward_backward(self):
        class Cube(paddle.autograd.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 3 * x * x

        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = Cube.apply(x)
        assert y.item() == pytest.approx(8.0)
        y.backward()
        assert x.grad.item() == pytest.approx(12.0)

    def test_pylayer_multi_io(self):
        class AddMul(paddle.autograd.PyLayer):
            @staticmethod
            def forward(ctx, a, b):
                ctx.save_for_backward(a, b)
                return a + b, a * b

            @staticmethod
            def backward(ctx, da, dm):
                a, b = ctx.saved_tensor()
                return da + dm * b, da + dm * a

        a = paddle.to_tensor(2.0, stop_gradient=False)
        b = paddle.to_tensor(5.0, stop_gradient=False)
        s, m = AddMul.apply(a, b)
        (s + m).backward()
        assert a.grad.item() == pytest.approx(6.0)
        assert b.grad.item() == pytest.approx(3.0)


class TestHigherOrder:
    def test_jacobian(self):
        x = paddle.to_tensor([1.0, 2.0])
        jac = paddle.autograd.jacobian(lambda t: t * t, x)
        np.testing.assert_allclose(jac.numpy(), np.diag([2.0, 4.0]))

    def test_hessian(self):
        x = paddle.to_tensor([1.0, 2.0])
        hes = paddle.autograd.hessian(lambda t: (t ** 3).sum(), x)
        np.testing.assert_allclose(hes.numpy(), np.diag([6.0, 12.0]))

    def test_vjp_jvp(self):
        x = paddle.to_tensor([1.0, 2.0])
        out, g = paddle.autograd.vjp(lambda t: (t * t).sum(), x)
        np.testing.assert_allclose(g.numpy(), [2.0, 4.0])
        out, tang = paddle.autograd.jvp(lambda t: (t * t).sum(), x)
        assert tang.item() == pytest.approx(6.0)


class TestDoubleGrad:
    """create_graph=True: backward steps recorded on the tape.
    ref: paddle/fluid/eager/backward.cc:439 general_grad."""

    def test_second_order(self):
        x = paddle.to_tensor(3.0, stop_gradient=False)
        y = x * x * x
        g, = paddle.grad(y, [x], create_graph=True)
        assert g.item() == pytest.approx(27.0)
        assert not g.stop_gradient
        gg, = paddle.grad(g, [x])
        assert gg.item() == pytest.approx(18.0)

    def test_third_order(self):
        x = paddle.to_tensor(3.0, stop_gradient=False)
        y = x * x * x
        g1, = paddle.grad(y, [x], create_graph=True)
        g2, = paddle.grad(g1, [x], create_graph=True)
        g3, = paddle.grad(g2, [x])
        assert g3.item() == pytest.approx(6.0)

    def test_mixed_partial(self):
        a = paddle.to_tensor(2.0, stop_gradient=False)
        b = paddle.to_tensor(5.0, stop_gradient=False)
        z = a * a * b
        ga, = paddle.grad(z, [a], create_graph=True)
        assert ga.item() == pytest.approx(20.0)
        gab, = paddle.grad(ga, [b])
        assert gab.item() == pytest.approx(4.0)

    def test_matches_jax_composition(self):
        import jax
        import jax.numpy as jnp
        f = lambda t: jnp.sum(jnp.sin(t) * t)
        xv = np.array([0.3, 1.1, -0.7], dtype=np.float32)
        expect = jax.grad(lambda t: jnp.sum(jax.grad(f)(t) ** 2))(xv)
        x = paddle.to_tensor(xv, stop_gradient=False)
        y = (paddle.sin(x) * x).sum()
        g, = paddle.grad(y, [x], create_graph=True)
        z = (g * g).sum()
        gg, = paddle.grad(z, [x])
        np.testing.assert_allclose(gg.numpy(), np.asarray(expect), rtol=1e-5)

    def test_vector_double_grad_through_matmul(self):
        w = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                             stop_gradient=False)
        x = paddle.to_tensor(np.ones((3, 2), dtype=np.float32),
                             stop_gradient=False)
        y = paddle.matmul(w, x).sum()
        gw, = paddle.grad(y, [w], create_graph=True)
        # d(sum(gw*gw))/dw == 0 (gw independent of w), but w.r.t. x it is too;
        # instead check gw value and that a further grad through gw*w works
        z = (gw * w).sum()
        gx, = paddle.grad(z, [w])
        np.testing.assert_allclose(gx.numpy(), gw.numpy())


class TestDoubleGradEdgeCases:
    def test_hook_stays_differentiable(self):
        x = paddle.to_tensor(3.0, stop_gradient=False)
        x.register_hook(lambda g: g * 2)
        y = x * x * x
        g, = paddle.grad(y, [x], create_graph=True)
        assert g.item() == pytest.approx(54.0)   # hook doubles 3x^2
        gg, = paddle.grad(g, [x])
        # hook fires on every backward: 2 * d(6x^2)/dx = 2 * 12x
        assert gg.item() == pytest.approx(72.0)

    def test_inputs_freed_after_plain_backward(self):
        a = paddle.to_tensor(2.0, stop_gradient=False)
        b = a * a
        b.backward()
        with pytest.raises(RuntimeError, match="already freed"):
            paddle.grad(b, [a], create_graph=True)

    def test_retain_graph_keeps_double_grad_alive(self):
        a = paddle.to_tensor(2.0, stop_gradient=False)
        c = a * a
        c.backward(retain_graph=True)
        g, = paddle.grad(c, [a], create_graph=True)
        assert g.item() == pytest.approx(4.0)



# Transient resource failures must not permanently demote an op to the
# plain eager path (ADVICE r4: autograd.py fast-dispatch NOJIT pinning),
# while trace-type errors settle immediately.

def test_mark_nojit_trace_error_settles_immediately():
    from paddle_tpu.core.autograd import _mark_nojit, _NOJIT
    cache, key = {}, ((), (), ())
    _mark_nojit(cache, key, TypeError("not traceable"))
    assert cache[key] is _NOJIT


def test_mark_nojit_transient_error_retries_then_settles():
    from paddle_tpu.core.autograd import _mark_nojit, _NOJIT
    cache, key = {}, ((), (), ())
    oom = RuntimeError("RESOURCE_EXHAUSTED: out of HBM")
    for _ in range(3):
        cache[key] = ("f", "b", {})  # rebuilt, never succeeded
        _mark_nojit(cache, key, oom)
        assert key not in cache  # evicted -> retried next dispatch
    cache[key] = ("f", "b", {})
    _mark_nojit(cache, key, oom)  # 4th consecutive failure
    assert cache[key] is _NOJIT
    assert key not in cache.get("_retry_counts", {})


def test_mark_nojit_confirmed_pair_survives_transient_failures():
    from paddle_tpu.core.autograd import _mark_nojit, _NOJIT
    cache, key = {}, ((), (), ())
    # has executed successfully at least once
    pair = ("f", "b", {"state": 1, "ever_ok": True})
    cache[key] = pair
    for _ in range(3):  # kept across the WHOLE retry budget
        _mark_nojit(cache, key, RuntimeError("RESOURCE_EXHAUSTED"))
        assert cache[key] is pair  # executable kept, no retrace
    assert pair[2]["state"] == 0  # next success must re-confirm
    _mark_nojit(cache, key, RuntimeError("RESOURCE_EXHAUSTED"))
    assert cache[key] is _NOJIT  # 4th consecutive failure settles


def test_mark_nojit_bookkeeping_does_not_crowd_pair_slots():
    from paddle_tpu.core.autograd import _mark_nojit
    cache = {}
    oom = RuntimeError("RESOURCE_EXHAUSTED")
    for i in range(40):
        key = ((), (i,), ())
        cache[key] = ("f", "b", {})
        _mark_nojit(cache, key, oom)
    # all counters share the single "_retry_counts" slot
    assert len(cache) == 1
