"""Sparse depth (VERDICT round-1 missing item 9): full paddle.sparse op
surface + sparse.nn conv/pool/norm/attention.

ref: python/paddle/sparse/ + phi/kernels/sparse/; oracles are the dense
equivalents (the submanifold contract checked explicitly).
"""
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

import paddle_tpu as paddle
import paddle_tpu.sparse as sparse
from paddle_tpu.sparse import SparseCooTensor


def _coo_from_dense(x, n_dense=0):
    return SparseCooTensor(jsparse.bcoo_fromdense(jnp.asarray(x),
                                                  n_dense=n_dense))


@pytest.mark.skipif(not os.path.isdir("/root/reference"),
                    reason="reference checkout absent in this container")
class TestSurface:
    def _ref_all(self, p):
        import ast
        t = ast.parse(open(p).read())
        for n in ast.walk(t):
            if isinstance(n, ast.Assign):
                for tg in n.targets:
                    if getattr(tg, "id", None) == "__all__":
                        return [ast.literal_eval(e) for e in n.value.elts]

    def test_sparse_all_covered(self):
        ref = self._ref_all(
            "/root/reference/python/paddle/sparse/__init__.py")
        assert [n for n in ref if not hasattr(sparse, n)] == []

    def test_sparse_nn_all_covered(self):
        ref = self._ref_all(
            "/root/reference/python/paddle/sparse/nn/__init__.py")
        assert [n for n in ref if not hasattr(sparse.nn, n)] == []


class TestOps:
    def _t(self):
        idx = np.array([[0, 1, 2], [1, 0, 2]])
        vals = np.array([0.5, -0.25, 0.75], np.float32)
        return sparse.sparse_coo_tensor(idx, vals, (3, 3)), idx, vals

    def test_unary_preserves_pattern(self):
        t, idx, vals = self._t()
        for name in ("sin", "tanh", "sqrt", "square", "abs", "neg",
                     "expm1", "log1p", "asinh", "atan"):
            fn = getattr(sparse, name)
            v = np.abs(vals) if name in ("sqrt", "log1p") else vals
            tt = sparse.sparse_coo_tensor(idx, v, (3, 3))
            out = fn(tt)
            assert out.nnz == 3
            ref = getattr(np, {"neg": "negative", "asinh": "arcsinh",
                               "atan": "arctan"}.get(name, name))(v)
            np.testing.assert_allclose(np.asarray(out.values()._data),
                                       ref, rtol=1e-5)

    def test_matmul_and_addmm(self):
        t, idx, vals = self._t()
        d = np.asarray(t.to_dense()._data)
        y = np.random.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(sparse.matmul(t, paddle.to_tensor(y))._data),
            d @ y, rtol=1e-5)
        inp = np.random.randn(3, 4).astype(np.float32)
        out = sparse.addmm(paddle.to_tensor(inp), t, paddle.to_tensor(y),
                           beta=0.5, alpha=2.0)
        np.testing.assert_allclose(np.asarray(out._data),
                                   0.5 * inp + 2.0 * (d @ y), rtol=1e-5)

    def test_mask_as_and_coalesce(self):
        t, idx, vals = self._t()
        dense = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(
            3, 3))
        masked = sparse.mask_as(dense, t)
        assert masked.nnz == 3
        got = np.asarray(masked.to_dense()._data)
        exp = np.zeros((3, 3), np.float32)
        exp[0, 1], exp[1, 0], exp[2, 2] = 1, 3, 8
        np.testing.assert_allclose(got, exp)
        # duplicate indices merge
        dup = sparse.sparse_coo_tensor(
            np.array([[0, 0], [1, 1]]), np.array([1.0, 2.0], np.float32),
            (2, 2))
        merged = sparse.coalesce(dup)
        np.testing.assert_allclose(
            np.asarray(merged.to_dense()._data)[0, 1], 3.0)

    def test_softmax_active_only(self):
        idx = np.array([[0, 0, 1], [0, 2, 1]])
        vals = np.array([1.0, 1.0, 5.0], np.float32)
        t = sparse.sparse_coo_tensor(idx, vals, (2, 3))
        sm = sparse.nn.functional.softmax(t)
        d = np.asarray(sm.to_dense()._data)
        np.testing.assert_allclose(d[0, 0], 0.5, rtol=1e-5)
        np.testing.assert_allclose(d[0, 2], 0.5, rtol=1e-5)
        np.testing.assert_allclose(d[1, 1], 1.0, rtol=1e-5)
        assert d[0, 1] == 0.0


class TestSparseNN:
    def test_conv3d_matches_dense_oracle(self):
        rng = np.random.default_rng(0)
        x = (rng.standard_normal((1, 4, 4, 4, 2)) *
             (rng.random((1, 4, 4, 4, 1)) > 0.6)).astype(np.float32)
        conv = sparse.nn.Conv3D(2, 3, 2)
        out = conv(_coo_from_dense(x, n_dense=1))
        w = np.asarray(conv.weight._data)
        b = np.asarray(conv.bias._data)
        dn = jax.lax.conv_dimension_numbers(
            x.shape, w.shape, ("NDHWC", "DHWIO", "NDHWC"))
        exp = np.asarray(jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1, 1), [(0, 0)] * 3,
            dimension_numbers=dn)) + b
        np.testing.assert_allclose(np.asarray(out.to_dense()._data), exp,
                                   rtol=1e-4, atol=1e-5)

    def test_subm_conv_preserves_active_set(self):
        x = np.zeros((1, 5, 5, 2), np.float32)
        x[0, 1, 1] = [1.0, 2.0]
        x[0, 3, 2] = [3.0, -1.0]
        conv = sparse.nn.SubmConv2D(2, 4, 3, padding=1)
        out = conv(_coo_from_dense(x, n_dense=1))
        od = np.asarray(out.to_dense()._data)
        active = np.broadcast_to(x.any(-1)[..., None], od.shape)
        assert (od[~active] == 0).all()
        assert np.abs(od[0, 1, 1]).sum() > 0

    def test_batchnorm_normalizes_values(self):
        rng = np.random.default_rng(0)
        x = (rng.standard_normal((1, 4, 4, 4, 3)) * 5 + 2).astype(
            np.float32) * (rng.random((1, 4, 4, 4, 1)) > 0.5)
        bn = sparse.nn.BatchNorm(3)
        out = bn(_coo_from_dense(x.astype(np.float32), n_dense=1))
        v = np.asarray(out.values()._data)
        np.testing.assert_allclose(v.mean(0), 0.0, atol=1e-4)
        np.testing.assert_allclose(v.std(0), 1.0, atol=1e-2)

    def test_maxpool3d(self):
        rng = np.random.default_rng(1)
        active = rng.random((1, 4, 4, 4, 1)) > 0.5
        x = (rng.standard_normal((1, 4, 4, 4, 2)) * active).astype(
            np.float32)
        out = sparse.nn.MaxPool3D(2)(_coo_from_dense(x, n_dense=1))
        # oracle: max over ACTIVE sites only (-inf elsewhere), empty
        # windows -> 0 (dropped from the sparse result)
        masked = np.where(np.broadcast_to(active, x.shape), x, -np.inf)
        exp = np.asarray(jax.lax.reduce_window(
            jnp.asarray(masked), -jnp.inf, jax.lax.max,
            (1, 2, 2, 2, 1), (1, 2, 2, 2, 1), "VALID"))
        exp = np.where(np.isfinite(exp), exp, 0.0)
        np.testing.assert_allclose(np.asarray(out.to_dense()._data), exp)

    def test_activation_layers(self):
        x = np.array([[-1.0, 0.0], [0.0, 7.0]], np.float32)
        t = _coo_from_dense(x)
        np.testing.assert_allclose(
            np.asarray(sparse.nn.ReLU()(t).to_dense()._data),
            np.maximum(x, 0))
        np.testing.assert_allclose(
            np.asarray(sparse.nn.ReLU6()(t).to_dense()._data),
            np.clip(x, 0, 6))

    def test_attention_matches_dense_oracle(self):
        rng = np.random.default_rng(0)
        B, H, L, D = 1, 2, 4, 8
        q = rng.standard_normal((B, H, L, D)).astype(np.float32)
        k = rng.standard_normal((B, H, L, D)).astype(np.float32)
        v = rng.standard_normal((B, H, L, D)).astype(np.float32)
        mask = (rng.random((B * H, L, L)) > 0.3).astype(np.float32)
        mask[:, 0, :] = 1.0  # no fully-masked rows
        out = sparse.nn.functional.attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            _coo_from_dense(mask))
        logits = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(D)
        logits = np.where(mask.reshape(B, H, L, L) != 0, logits, -1e30)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        p = np.where(mask.reshape(B, H, L, L).any(-1, keepdims=True),
                     p, 0.0)
        np.testing.assert_allclose(np.asarray(out._data), p @ v,
                                   rtol=1e-4, atol=1e-5)

    def test_training_through_sparse_conv(self):
        rng = np.random.default_rng(0)
        x = (rng.standard_normal((1, 5, 5, 2)) *
             (rng.random((1, 5, 5, 1)) > 0.5)).astype(np.float32)
        net = sparse.nn.SubmConv2D(2, 2, 3, padding=1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        losses = []
        for _ in range(5):
            out = net(_coo_from_dense(x, n_dense=1))
            loss = (out.values() ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestReviewRegressions:
    def test_leaky_relu_slope_respected(self):
        x = np.array([[-1.0, 0.0], [0.0, 2.0]], np.float32)
        out = sparse.nn.LeakyReLU(0.2)(_coo_from_dense(x))
        np.testing.assert_allclose(
            np.asarray(out.to_dense()._data),
            np.where(x >= 0, x, 0.2 * x), rtol=1e-6)

    def test_maxpool_all_negative_active_window(self):
        """Active-sites-only max: implicit zeros must NOT win over
        negative active values (reference sparse maxpool contract)."""
        x = np.zeros((1, 2, 2, 2, 1), np.float32)
        x[0, 0, 0, 0, 0] = -3.0
        out = sparse.nn.MaxPool3D(2)(_coo_from_dense(x, n_dense=1))
        np.testing.assert_allclose(
            np.asarray(out.to_dense()._data).reshape(-1), [-3.0])

    def test_relu_preserves_layout_flags(self):
        idx = np.array([[0, 1], [0, 1]])
        t = sparse.sparse_coo_tensor(idx, np.array([-1.0, 2.0], np.float32),
                                     (2, 2))
        out = sparse.relu(t)
        assert out._data.indices_sorted == t._data.indices_sorted
