"""Vision model zoo + metric tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall, accuracy
from paddle_tpu.vision.models import (
    LeNet, MobileNetV2, resnet18, vgg11,
)
from paddle_tpu.vision import transforms
from paddle_tpu.vision.datasets import MNIST


def test_lenet_forward_backward():
    m = LeNet()
    x = paddle.to_tensor(np.random.randn(2, 1, 28, 28).astype(np.float32),
                         stop_gradient=False)
    out = m(x)
    assert out.shape == [2, 10]
    out.sum().backward()
    assert m.features[0].weight.grad is not None


def test_resnet18_forward():
    m = resnet18(num_classes=10)
    m.eval()
    x = paddle.to_tensor(np.random.randn(2, 3, 64, 64).astype(np.float32))
    assert m(x).shape == [2, 10]


def test_resnet_state_dict_structure():
    m = resnet18(num_classes=10)
    sd = m.state_dict()
    assert "conv1.weight" in sd
    assert "layer1.0.conv1.weight" in sd
    assert "fc.weight" in sd


def test_resnet_nhwc_matches_nchw():
    """data_format='NHWC' (the TPU-native conv layout used by bench.py)
    must be numerically identical to NCHW — same weights, transposed
    input/activations only."""
    x = np.random.default_rng(0).standard_normal((2, 3, 64, 64)).astype(
        np.float32)
    paddle.seed(0)
    m1 = resnet18(num_classes=10)
    m1.eval()
    paddle.seed(0)
    m2 = resnet18(num_classes=10, data_format="NHWC")
    m2.eval()
    o1 = m1(paddle.to_tensor(x)).numpy()
    o2 = m2(paddle.to_tensor(np.transpose(x, (0, 2, 3, 1)))).numpy()
    np.testing.assert_allclose(o1, o2, atol=2e-4)
    # NHWC state dict keys/shapes identical (weights stay OIHW)
    assert {k: tuple(v.shape) for k, v in m1.state_dict().items()} == \
        {k: tuple(v.shape) for k, v in m2.state_dict().items()}
    # train-mode fwd/bwd works and running stats update
    m2.train()
    before = m2.bn1._mean.numpy().copy()
    out = m2(paddle.to_tensor(np.transpose(x, (0, 2, 3, 1))))
    (out ** 2).mean().backward()
    assert m2.conv1.weight.grad is not None
    assert np.isfinite(m2.conv1.weight.grad.numpy()).all()
    assert not np.array_equal(before, m2.bn1._mean.numpy())


@pytest.mark.slow
def test_mobilenet_vgg_forward():
    x = paddle.to_tensor(np.random.randn(1, 3, 64, 64).astype(np.float32))
    assert MobileNetV2(num_classes=7)(x).shape == [1, 7]
    assert vgg11(num_classes=5)(
        paddle.to_tensor(np.random.randn(1, 3, 224, 224).astype(np.float32))
    ).shape == [1, 5]


def test_transforms_pipeline():
    t = transforms.Compose([
        transforms.Resize(32),
        transforms.CenterCrop(28),
        transforms.RandomHorizontalFlip(0.5),
        transforms.ToTensor(),
        transforms.Normalize(mean=[0.5] * 3, std=[0.5] * 3),
    ])
    img = (np.random.rand(48, 56, 3) * 255).astype(np.uint8)
    out = t(img)
    assert out.shape == (3, 28, 28)
    assert out.dtype == np.float32


def test_dataset_dataloader():
    ds = MNIST(mode="train", transform=transforms.ToTensor())
    img, label = ds[0]
    assert img.shape == (1, 28, 28)
    loader = paddle.io.DataLoader(ds, batch_size=16, shuffle=True)
    batch_img, batch_label = next(iter(loader))
    assert np.asarray(batch_img).shape == (16, 1, 28, 28)
    assert np.asarray(batch_label).shape == (16, 1)


def test_accuracy_metric():
    acc = Accuracy(topk=(1, 2))
    pred = paddle.to_tensor(np.array(
        [[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]], np.float32))
    label = paddle.to_tensor(np.array([[1], [2]], np.int64))
    correct = acc.compute(pred, label)
    acc.update(correct)
    top1, top2 = acc.accumulate()
    assert top1 == pytest.approx(0.5)
    assert top2 == pytest.approx(0.5)  # sample2's label 2 not in top2? idx=[0,2] contains 2 -> 1.0
    acc.reset()
    assert acc.count == [0, 0]


def test_accuracy_functional():
    pred = paddle.to_tensor(np.array(
        [[0.1, 0.9], [0.9, 0.1]], np.float32))
    label = paddle.to_tensor(np.array([[1], [0]], np.int64))
    a = accuracy(pred, label, k=1)
    assert float(a.item()) == pytest.approx(1.0)


def test_precision_recall_auc():
    p = Precision()
    r = Recall()
    preds = np.array([0.9, 0.8, 0.2, 0.1], np.float32)
    labels = np.array([1, 0, 1, 0], np.int64)
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.accumulate() == pytest.approx(0.5)
    assert r.accumulate() == pytest.approx(0.5)
    auc = Auc()
    auc.update(preds, labels)
    assert 0.0 <= auc.accumulate() <= 1.0


def test_nms():
    from paddle_tpu.vision.ops import nms
    boxes = paddle.to_tensor(np.array([
        [0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]], np.float32))
    scores = paddle.to_tensor(np.array([0.9, 0.8, 0.7], np.float32))
    keep = nms(boxes, iou_threshold=0.3, scores=scores)
    assert np.asarray(keep._data).tolist() == [0, 2]
