"""Fleet hybrid-parallel tests (topology math, TP layers, sharding opt,
pipeline segmentation). ref test strategy: test/collective/fleet/."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import (
    CommunicateTopology, HybridCommunicateGroup, LayerDesc, PipelineLayer,
)


def test_topology_math():
    # ref: topology.py coordinate/rank bijection
    topo = CommunicateTopology(["dp", "pp", "sharding", "sep", "mp"],
                               [2, 2, 1, 1, 2])
    assert topo.world_size() == 8
    assert topo.get_rank(dp=0, pp=0, sharding=0, sep=0, mp=0) == 0
    assert topo.get_rank(dp=1, pp=1, sharding=0, sep=0, mp=1) == 7
    assert topo.get_coord(5) == (1, 0, 0, 0, 1)
    # comm lists along mp: consecutive pairs
    mp_lists = topo.get_comm_list("mp")
    assert [0, 1] in mp_lists
    assert all(len(g) == 2 for g in mp_lists)
    dp_lists = topo.get_comm_list("dp")
    assert [0, 4] in dp_lists


def test_hybrid_communicate_group():
    topo = CommunicateTopology(["dp", "pp", "sharding", "sep", "mp"],
                               [2, 1, 2, 1, 2])
    hcg = HybridCommunicateGroup(topo, global_rank=0)
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_sharding_parallel_world_size() == 2
    assert hcg.get_data_parallel_rank() == 0
    assert hcg.is_first_stage() and hcg.is_last_stage()
    mesh = hcg.get_mesh()
    assert mesh.dim_names == ["dp", "sharding", "mp"]
    assert mesh.size == 8


def test_fleet_init_and_tp_layers():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 4

    col = fleet.ColumnParallelLinear(16, 32, gather_output=True)
    row = fleet.RowParallelLinear(32, 16)
    assert col.weight._dist_attr is not None  # mp-sharded
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32),
                         stop_gradient=False)
    out = row(col(x))
    assert out.shape == [4, 16]
    out.sum().backward()
    assert col.weight.grad is not None

    emb = fleet.VocabParallelEmbedding(128, 16)
    ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], np.int32))
    e = emb(ids)
    assert e.shape == [2, 2, 16]

    # numerics match plain layers with identical weights
    lin = nn.Linear(16, 32)
    lin.weight.set_value(col.weight)
    lin.bias.set_value(col.bias)
    np.testing.assert_allclose(np.asarray(col(x)._data),
                               np.asarray(lin(x)._data), rtol=2e-5,
                               atol=2e-5)


def test_parallel_cross_entropy():
    pce = fleet.ParallelCrossEntropy()
    logits = paddle.to_tensor(np.random.randn(4, 10).astype(np.float32))
    labels = paddle.to_tensor(np.array([1, 2, 3, 4], np.int64))
    loss = pce(logits, labels)
    ref = paddle.nn.functional.cross_entropy(
        logits, labels, reduction="none")
    np.testing.assert_allclose(np.asarray(loss._data).squeeze(),
                               np.asarray(ref._data).squeeze(), rtol=1e-5,
                               atol=1e-5)


def test_sharding_optimizer_partition():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 8))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    dopt = fleet.distributed_optimizer(opt)
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
    loss = model(x).sum()
    loss.backward()
    dopt.step()
    dopt.clear_grad()
    # greedy partition covers every param exactly once
    inner = dopt._inner_opt
    seen = set()
    for plist in inner._rank2params:
        for p in plist:
            assert id(p) not in seen
            seen.add(id(p))
    assert len(seen) == len(model.parameters())


def test_pipeline_layer_segmentation():
    descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(7)]
    pl = PipelineLayer(descs, num_stages=1)
    assert len(pl.run_function) == 7
    # segment bounds for 2 stages: 4 + 3
    from paddle_tpu.distributed.fleet.pp_layers import _uniform_partition
    assert _uniform_partition(7, 2) == [0, 4, 7]
    assert _uniform_partition(8, 4) == [0, 2, 4, 6, 8]
    x = paddle.to_tensor(np.ones((2, 8), np.float32))
    assert pl(x).shape == [2, 8]


def test_pipeline_parallel_train_batch():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1}
    strategy.pipeline_configs = {"accumulate_steps": 4,
                                 "micro_batch_size": 2}
    fleet.init(is_collective=True, strategy=strategy)
    pl = PipelineLayer([LayerDesc(nn.Linear, 8, 8),
                        LayerDesc(nn.Linear, 8, 1)],
                       num_stages=1,
                       loss_fn=nn.MSELoss())
    from paddle_tpu.distributed.fleet.pipeline_parallel import PipelineParallel
    hcg = fleet.get_hybrid_communicate_group()
    model = PipelineParallel(pl, hcg, strategy)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=pl.parameters())
    x = np.random.randn(8, 8).astype(np.float32)
    w = np.random.randn(8, 1).astype(np.float32)
    y = x @ w
    losses = []
    for _ in range(60):
        loss = model.train_batch(
            (paddle.to_tensor(x), paddle.to_tensor(y)), opt)
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0] * 0.2, losses[::20]


def test_distributed_split_api():
    import paddle_tpu.distributed as dist
    x = paddle.to_tensor(np.random.randn(2, 16).astype(np.float32))
    out = dist.split(x, (16, 8), operation="linear", axis=1)
    assert out.shape == [2, 8]
