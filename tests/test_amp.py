"""AMP tests: auto_cast autocasting, GradScaler dynamic loss scaling.

ref: the reference exercises AMP through test/amp/ (O1/O2 lists,
check_finite_and_unscale, dynamic loss scale update)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestAutoCast:
    def test_matmul_autocasts_to_bf16(self, rng):
        x = paddle.to_tensor(rng.normal(size=(4, 4)).astype(np.float32))
        y = paddle.to_tensor(rng.normal(size=(4, 4)).astype(np.float32))
        with paddle.amp.auto_cast(level="O1"):
            out = paddle.matmul(x, y)
        assert str(out.dtype) == "bfloat16"
        # outside the context: fp32 again
        out2 = paddle.matmul(x, y)
        assert str(out2.dtype) == "float32"

    def test_blacklisted_op_stays_fp32(self, rng):
        x = paddle.to_tensor(rng.normal(size=(8,)).astype(np.float32))
        with paddle.amp.auto_cast(level="O1"):
            s = paddle.nn.functional.softmax(x)
        assert str(s.dtype) == "float32"

    def test_training_under_autocast_converges(self, rng):
        m = paddle.nn.Sequential(paddle.nn.Linear(4, 16),
                                 paddle.nn.ReLU(),
                                 paddle.nn.Linear(16, 1))
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=m.parameters())
        X = paddle.to_tensor(rng.normal(size=(32, 4)).astype(np.float32))
        yt = paddle.to_tensor(
            (rng.normal(size=(32, 1))).astype(np.float32))
        first = None
        for _ in range(60):
            with paddle.amp.auto_cast():
                out = m(X)
                loss = ((out.astype("float32") - yt) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss)
        assert float(loss) < first


class TestDtypePromotion:
    def test_mixed_dtype_conv_promotes_like_linear(self, rng):
        """fp32 input x bf16 conv weights promotes to fp32, the same
        semantics as F.linear's `x @ w` (regression: lax.conv used to
        reject mixed dtypes; then an early fix silently downcast)."""
        m = paddle.nn.Conv2D(3, 8, 3)
        m.bfloat16()
        x = paddle.to_tensor(
            rng.normal(size=(1, 3, 8, 8)).astype(np.float32))
        out = m(x)
        assert str(out.dtype) == "float32"
        # fully-bf16 path stays bf16
        out_bf16 = m(x.astype("bfloat16"))
        assert str(out_bf16.dtype) == "bfloat16"


class TestGradScaler:
    def test_scale_unscale_roundtrip(self, rng):
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
        p = paddle.Parameter(np.ones(4, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
        loss = (p * p).sum()
        scaled = scaler.scale(loss)
        np.testing.assert_allclose(float(scaled), float(loss) * 1024.0,
                                   rtol=1e-6)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        # effective update == unscaled grad (2*p) * lr
        np.testing.assert_allclose(p.numpy(), 1.0 - 0.1 * 2.0, rtol=1e-5)

    def test_skips_step_on_nonfinite_and_backs_off(self):
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                       decr_every_n_nan_or_inf=1)
        p = paddle.Parameter(np.ones(2, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
        p.grad = paddle.to_tensor(
            np.array([np.inf, 1.0], np.float32))
        before = p.numpy().copy()
        scaler.step(opt)
        scaler.update()
        np.testing.assert_array_equal(p.numpy(), before)  # step skipped
        assert scaler.get_loss_scaling() < 1024.0         # scale backed off

    def test_scale_grows_after_good_steps(self):
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0,
                                       incr_every_n_steps=2)
        p = paddle.Parameter(np.ones(2, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[p])
        for _ in range(4):
            loss = (p * p).sum()
            scaler.scale(loss).backward()
            scaler.step(opt)
            scaler.update()
            opt.clear_grad()
        assert scaler.get_loss_scaling() > 8.0

    def test_static_scaling_recovers_after_inf(self):
        """use_dynamic_loss_scaling=False: one non-finite step must not
        latch the found flag — the next finite step updates again."""
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0,
                                       use_dynamic_loss_scaling=False)
        p = paddle.Parameter(np.ones(2, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
        p.grad = paddle.to_tensor(np.array([np.inf, 1.0], np.float32))
        scaler.step(opt)
        scaler.update()
        np.testing.assert_array_equal(p.numpy(), 1.0)  # skipped
        p.grad = paddle.to_tensor(np.array([8.0, 8.0], np.float32))
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(p.numpy(), 1.0 - 0.1, rtol=1e-6)
        assert float(scaler.get_loss_scaling()) == 8.0  # static scale

    def test_scale_preserves_loss_dtype(self):
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
        loss16 = paddle.to_tensor(np.float16(2.0))
        assert str(scaler.scale(loss16).dtype) == "float16"
        lossbf = paddle.to_tensor(np.ones((2,), np.float32)).astype(
            "bfloat16").sum()
        assert str(scaler.scale(lossbf).dtype) == "bfloat16"

    @pytest.mark.parametrize("fused", [1, 0], ids=["fused", "eager"])
    def test_step_and_update_never_sync_to_host(self, fused):
        """The finite check's skip decision stays on device: zero
        device->host transfers inside scaler.step()+update(), on the
        fused path AND the FLAGS_fused_optimizer=0 fallback (regression:
        step() used to call bool(all(isfinite(g))) per step)."""
        import jax.numpy as jnp
        prev = paddle.get_flags("FLAGS_fused_optimizer")
        paddle.set_flags({"FLAGS_fused_optimizer": fused})
        cls = type(jnp.zeros(()))
        transfers = [0]
        hooked = {}

        def hook(name):
            orig = getattr(cls, name)
            hooked[name] = orig

            def counted(self, *a, **kw):
                transfers[0] += 1
                return orig(self, *a, **kw)
            return counted

        try:
            p = paddle.Parameter(np.ones(8, np.float32))
            opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
            scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)
            for _ in range(3):
                scaler.scale((p * p).sum()).backward()
                for name in ("__bool__", "__float__", "__index__",
                             "__array__"):
                    setattr(cls, name, hook(name))
                try:
                    scaler.step(opt)
                    scaler.update()
                finally:
                    for name, orig in hooked.items():
                        setattr(cls, name, orig)
                opt.clear_grad()
            assert transfers[0] == 0
        finally:
            paddle.set_flags(prev)

    @pytest.mark.parametrize("fused", [1, 0], ids=["fused", "eager"])
    def test_multi_optimizer_shared_scaler_skip_agrees(self, fused):
        """One scaler, two optimizers: optA's inf must also skip optB —
        the fallback masks by the OR-accumulated flag, and the fused
        fast path must reach the same decision (regression: it used to
        mask only by its own finite check)."""
        prev = paddle.get_flags("FLAGS_fused_optimizer")
        paddle.set_flags({"FLAGS_fused_optimizer": fused})
        try:
            scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
            pa = paddle.Parameter(np.ones(2, np.float32))
            pb = paddle.Parameter(np.ones(2, np.float32))
            opt_a = paddle.optimizer.SGD(learning_rate=0.1, parameters=[pa])
            opt_b = paddle.optimizer.SGD(learning_rate=0.1, parameters=[pb])
            pa.grad = paddle.to_tensor(np.array([np.inf, 4.0], np.float32))
            pb.grad = paddle.to_tensor(np.array([4.0, 4.0], np.float32))
            scaler.step(opt_a)
            scaler.step(opt_b)
            scaler.update()
            np.testing.assert_array_equal(pa.numpy(), 1.0)  # skipped
            np.testing.assert_array_equal(pb.numpy(), 1.0)  # also skipped
        finally:
            paddle.set_flags(prev)

    @pytest.mark.parametrize("fused", [1, 0], ids=["fused", "eager"])
    def test_frozen_param_grad_joins_finite_check(self, fused):
        """A stop_gradient param still holding a grad: its inf must
        trigger the skip and its grad must come back unscaled on BOTH
        flag settings (regression: the fused path neither checked nor
        unscaled frozen params' grads)."""
        prev = paddle.get_flags("FLAGS_fused_optimizer")
        paddle.set_flags({"FLAGS_fused_optimizer": fused})
        try:
            scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
            p = paddle.Parameter(np.ones(2, np.float32))
            frozen = paddle.Parameter(np.ones(2, np.float32))
            frozen.stop_gradient = True
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=[p, frozen])
            p.grad = paddle.to_tensor(np.array([4.0, 4.0], np.float32))
            frozen.grad = paddle.to_tensor(
                np.array([np.inf, 4.0], np.float32))
            scaler.step(opt)
            scaler.update()
            np.testing.assert_array_equal(p.numpy(), 1.0)  # skipped
            np.testing.assert_array_equal(
                frozen.grad.numpy(), np.array([np.inf, 1.0], np.float32))
        finally:
            paddle.set_flags(prev)

    @pytest.mark.parametrize("fused", [1, 0], ids=["fused", "eager"])
    def test_recovers_without_update_call(self, fused):
        """A loop that never calls update() (static scaling makes it
        look optional) must still recover after one bad batch — the
        next iteration's scale() clears the OR-accumulated flag
        (regression: the accumulator latched True forever)."""
        prev = paddle.get_flags("FLAGS_fused_optimizer")
        paddle.set_flags({"FLAGS_fused_optimizer": fused})
        try:
            scaler = paddle.amp.GradScaler(
                init_loss_scaling=4.0, use_dynamic_loss_scaling=False)
            p = paddle.Parameter(np.ones(2, np.float32))
            opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
            scaler.scale((p * p).sum()).backward()
            p.grad = paddle.to_tensor(np.array([np.inf, 4.0], np.float32))
            scaler.step(opt)          # skipped; no update() follows
            opt.clear_grad()
            np.testing.assert_array_equal(p.numpy(), 1.0)
            scaler.scale((p * p).sum()).backward()  # finite batch
            scaler.step(opt)
            opt.clear_grad()
            # grad of sum(p*p) is 2p -> p = 1 - 0.1*2
            np.testing.assert_allclose(p.numpy(), 0.8, rtol=1e-6)
        finally:
            paddle.set_flags(prev)

    @pytest.mark.parametrize("fused", [1, 0], ids=["fused", "eager"])
    def test_unscale_without_step_does_not_latch(self, fused):
        """An iteration that calls unscale_ (grad inspection) but skips
        step() must not leak its unscale mark past update(): a stale id
        would early-return the next iteration's unscale_ and step()
        would apply still-scaled grads (regression: p went to -11.8
        instead of 0.8 at scale=64)."""
        prev = paddle.get_flags("FLAGS_fused_optimizer")
        paddle.set_flags({"FLAGS_fused_optimizer": fused})
        try:
            scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)
            p = paddle.Parameter(np.ones(2, np.float32))
            opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
            # iter 1: unscale to inspect grads, then skip the step
            scaler.scale((p * p).sum()).backward()
            scaler.unscale_(opt)
            opt.clear_grad()
            scaler.update()
            # iter 2: normal step — grads must be unscaled exactly once
            scaler.scale((p * p).sum()).backward()
            scaler.step(opt)
            scaler.update()
            opt.clear_grad()
            # grad of sum(p*p) is 2p -> p = 1 - 0.1*2
            np.testing.assert_allclose(p.numpy(), 0.8, rtol=1e-6)
        finally:
            paddle.set_flags(prev)

    def test_custom_step_override_runs_under_scaler(self):
        """An optimizer subclass implementing step() directly (no
        _update hook — the LBFGS pattern) must have its override run
        under scaler.step(), and still skip on inf (regression: the
        device-masked fallback bypassed step() and hit
        _update's NotImplementedError)."""
        calls = []

        class StepOnly(paddle.optimizer.Optimizer):
            def step(self):
                calls.append(1)
                for p in self._parameter_list:
                    if p.grad is not None:
                        p.set_value(p.numpy() - 0.1 * p.grad.numpy())

        p = paddle.Parameter(np.ones(2, np.float32))
        opt = StepOnly(learning_rate=0.1, parameters=[p])
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        scaler.scale((p * p).sum()).backward()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        assert calls == [1]
        np.testing.assert_allclose(p.numpy(), 0.8, rtol=1e-6)
        p.grad = paddle.to_tensor(np.array([np.inf, 4.0], np.float32))
        before = p.numpy().copy()
        scaler.step(opt)
        scaler.update()
        assert calls == [1]  # override NOT called on a non-finite step
        np.testing.assert_array_equal(p.numpy(), before)

    def test_shard_optimizer_wrapper_steps_under_scaler(self):
        """scaler.step(shard_optimizer(...)): the wrapper is not an
        Optimizer subclass and has NO class-level step — it delegates
        through instance __getattr__. Override detection must treat it
        like a custom step, not crash on a missing class attr
        (regression: AttributeError on type(_ShardOptimizer).step)."""
        from paddle_tpu.distributed.auto_parallel.api_ext import (
            shard_optimizer)
        p = paddle.Parameter(np.ones(2, np.float32))
        opt = shard_optimizer(
            paddle.optimizer.SGD(learning_rate=0.1, parameters=[p]))
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        scaler.scale((p * p).sum()).backward()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        np.testing.assert_allclose(p.numpy(), 0.8, rtol=1e-6)
        p.grad = paddle.to_tensor(np.array([np.inf, 4.0], np.float32))
        before = p.numpy().copy()
        scaler.step(opt)
        scaler.update()
        np.testing.assert_array_equal(p.numpy(), before)  # skipped

    def test_patched_unscale_still_runs(self):
        """An instance-patched unscale_ (distributed shard_scaler wraps
        it to allreduce found_inf) must run inside step() — the fused
        fast path would silently bypass it."""
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        p = paddle.Parameter(np.ones(4, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
        calls = []
        orig = scaler.unscale_
        scaler.unscale_ = lambda o: (calls.append(id(o)), orig(o))[1]
        scaler.scale((p * p).sum()).backward()
        scaler.step(opt)
        scaler.update()
        assert calls == [id(opt)]
        np.testing.assert_allclose(p.numpy(), 1.0 - 0.1 * 2.0, rtol=1e-5)
