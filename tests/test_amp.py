"""AMP tests: auto_cast autocasting, GradScaler dynamic loss scaling.

ref: the reference exercises AMP through test/amp/ (O1/O2 lists,
check_finite_and_unscale, dynamic loss scale update)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestAutoCast:
    def test_matmul_autocasts_to_bf16(self, rng):
        x = paddle.to_tensor(rng.normal(size=(4, 4)).astype(np.float32))
        y = paddle.to_tensor(rng.normal(size=(4, 4)).astype(np.float32))
        with paddle.amp.auto_cast(level="O1"):
            out = paddle.matmul(x, y)
        assert str(out.dtype) == "bfloat16"
        # outside the context: fp32 again
        out2 = paddle.matmul(x, y)
        assert str(out2.dtype) == "float32"

    def test_blacklisted_op_stays_fp32(self, rng):
        x = paddle.to_tensor(rng.normal(size=(8,)).astype(np.float32))
        with paddle.amp.auto_cast(level="O1"):
            s = paddle.nn.functional.softmax(x)
        assert str(s.dtype) == "float32"

    def test_training_under_autocast_converges(self, rng):
        m = paddle.nn.Sequential(paddle.nn.Linear(4, 16),
                                 paddle.nn.ReLU(),
                                 paddle.nn.Linear(16, 1))
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=m.parameters())
        X = paddle.to_tensor(rng.normal(size=(32, 4)).astype(np.float32))
        yt = paddle.to_tensor(
            (rng.normal(size=(32, 1))).astype(np.float32))
        first = None
        for _ in range(60):
            with paddle.amp.auto_cast():
                out = m(X)
                loss = ((out.astype("float32") - yt) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss)
        assert float(loss) < first


class TestDtypePromotion:
    def test_mixed_dtype_conv_promotes_like_linear(self, rng):
        """fp32 input x bf16 conv weights promotes to fp32, the same
        semantics as F.linear's `x @ w` (regression: lax.conv used to
        reject mixed dtypes; then an early fix silently downcast)."""
        m = paddle.nn.Conv2D(3, 8, 3)
        m.bfloat16()
        x = paddle.to_tensor(
            rng.normal(size=(1, 3, 8, 8)).astype(np.float32))
        out = m(x)
        assert str(out.dtype) == "float32"
        # fully-bf16 path stays bf16
        out_bf16 = m(x.astype("bfloat16"))
        assert str(out_bf16.dtype) == "bfloat16"


class TestGradScaler:
    def test_scale_unscale_roundtrip(self, rng):
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
        p = paddle.Parameter(np.ones(4, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
        loss = (p * p).sum()
        scaled = scaler.scale(loss)
        np.testing.assert_allclose(float(scaled), float(loss) * 1024.0,
                                   rtol=1e-6)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        # effective update == unscaled grad (2*p) * lr
        np.testing.assert_allclose(p.numpy(), 1.0 - 0.1 * 2.0, rtol=1e-5)

    def test_skips_step_on_nonfinite_and_backs_off(self):
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                       decr_every_n_nan_or_inf=1)
        p = paddle.Parameter(np.ones(2, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
        p.grad = paddle.to_tensor(
            np.array([np.inf, 1.0], np.float32))
        before = p.numpy().copy()
        scaler.step(opt)
        scaler.update()
        np.testing.assert_array_equal(p.numpy(), before)  # step skipped
        assert scaler.get_loss_scaling() < 1024.0         # scale backed off

    def test_scale_grows_after_good_steps(self):
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0,
                                       incr_every_n_steps=2)
        p = paddle.Parameter(np.ones(2, np.float32))
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[p])
        for _ in range(4):
            loss = (p * p).sum()
            scaler.scale(loss).backward()
            scaler.step(opt)
            scaler.update()
            opt.clear_grad()
        assert scaler.get_loss_scaling() > 8.0
