"""Static auto-parallel planner tests (ref: auto_parallel/static/engine
planner + static/cost/ + auto_tuner prune/trial flow): candidate
enumeration, memory pruning, cost-model preferences, the measured-trial
pick, and Engine auto-planning end-to-end on the 8-virtual-device
mesh."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel.planner import (
    Cluster, ModelProfile, PlanCandidate, Planner, profile_model)


class TestCandidatesAndPricing:
    def test_enumerates_factorizations(self):
        cands = Planner(8).candidates()
        shapes = {c.mesh_shape for c in cands}
        assert (8, 1, 1) in shapes and (1, 8, 1) in shapes and \
            (1, 1, 8) in shapes and (2, 2, 2) in shapes
        for c in cands:
            assert c.dp * c.fsdp * c.mp == 8

    def test_small_model_prefers_pure_dp(self):
        """Tiny model, plenty of memory: replication has the least
        communication, so dp wins (the dryrun-Llama case)."""
        prof = ModelProfile(param_bytes=10 * 2 ** 20,
                            flops_per_step=1e12, batch_tokens=2048,
                            hidden=256, layer_count=2)
        best = Planner(8).plan(prof, top_k=1)[0]
        assert best.mesh_shape == (8, 1, 1), best

    def test_memory_prune_forces_sharding(self):
        """A model whose optimizer state cannot replicate must come back
        with fsdp*mp sharding enough to fit — the compile-free OOM
        verdict."""
        # 3B params bf16: state ~ 6GB * 11 = 66GB; fits only sharded 8x
        prof = ModelProfile(param_bytes=6 * 10 ** 9,
                            flops_per_step=1e15, batch_tokens=4096,
                            hidden=4096, layer_count=32)
        planner = Planner(8)
        best = planner.plan(prof, top_k=1)[0]
        assert best.fsdp * best.mp == 8, best
        assert best.est_mem_bytes <= planner.cluster.hbm_bytes
        # and every feasible candidate indeed fits
        for c in planner.plan(prof, top_k=10):
            assert c.est_mem_bytes <= planner.cluster.hbm_bytes

    def test_infeasible_everywhere_raises(self):
        prof = ModelProfile(param_bytes=40 * 10 ** 9,
                            flops_per_step=1e15, batch_tokens=4096,
                            hidden=8192, layer_count=48)
        with pytest.raises(ValueError, match="no feasible"):
            Planner(8).plan(prof)

    def test_comm_model_penalizes_mp_for_long_activations(self):
        """Huge activation traffic (long sequences, many layers) with a
        small parameter footprint: mp's per-layer allreduces must price
        above fsdp's param traffic."""
        # 1B params bf16, fat hidden (no mp compute penalty), heavy
        # activation traffic: fsdp's 3x param bytes < mp's per-layer
        # activation allreduces, and replication cannot fit the state
        prof = ModelProfile(param_bytes=2 * 10 ** 9,
                            flops_per_step=1e15,
                            batch_tokens=64 * 1024, hidden=8192,
                            layer_count=64)
        planner = Planner(8)
        best = planner.plan(prof, top_k=1)[0]
        assert best.mp == 1, best
        assert best.fsdp > 1, best

    def test_pp_axis_enumerated_and_priced(self):
        """max_pp opens the pipeline axis: 4-tuples cover dp*fsdp*mp*pp
        = n, and pp candidates carry a schedule + simulator-derived
        bubble fraction (ref: passes/pipeline_scheduler_pass/)."""
        cands = Planner(8, max_pp=8).candidates()
        shapes = {c.full_shape for c in cands}
        assert (1, 1, 1, 8) in shapes and (2, 1, 1, 4) in shapes
        for c in cands:
            assert c.dp * c.fsdp * c.mp * c.pp == 8
        prof = ModelProfile(param_bytes=2 * 10 ** 9,
                            flops_per_step=1e15, batch_tokens=8192,
                            hidden=4096, layer_count=32)
        p = Planner(8, max_pp=8)
        priced = [p.price(c, prof) for c in p.candidates() if c.pp == 4]
        for c in priced:
            assert c.schedule in ("1f1b", "zb_h1")
            assert 0.0 < c.bubble_fraction < 1.0
            # ZB-H1's whole point: never a worse bubble than 1F1B
            from paddle_tpu.distributed.auto_parallel.planner import \
                _bubble_fractions
            f1b, zb = _bubble_fractions(4, 8)
            assert zb <= f1b

    def test_memory_infeasible_without_pp_plans_onto_pp(self):
        """The VERDICT done-gate: a model whose activation checkpoints
        can't fit however the BATCH is split (batch too small to spread
        over more dp*fsdp) must come back with pp > 1 — pipeline shards
        the LAYERS, the one memory lever the flat axes don't have."""
        # modest params, but enormous activation-checkpoint footprint:
        # 8192 tokens * hidden 32768 * 128 layers * 2B = 68.7GB of remat
        # checkpoints; dp*fsdp <= 4 dilutes it to 17.2GB > HBM however
        # the flat mesh is factored (mp shards neither checkpoints nor
        # their batch), while pp=4 stores only each stage's layers for
        # the in-flight micro-batches (~8.6GB)
        prof = ModelProfile(param_bytes=1 * 10 ** 9,
                            flops_per_step=1e15,
                            batch_tokens=8192, hidden=32768,
                            layer_count=128)
        # without pp: every flat config is memory-infeasible
        with pytest.raises(ValueError, match="no feasible"):
            Planner(4).plan(prof)
        # with the pipeline axis open, the planner finds a pp plan
        best = Planner(4, max_pp=4).plan(prof, top_k=1)[0]
        assert best.pp > 1, best
        assert best.est_mem_bytes <= Planner(4).cluster.hbm_bytes
        assert best.schedule in ("1f1b", "zb_h1")

    def test_plan_measured_reports_pp_config(self):
        """pp candidates reach the trial runner with their schedule in
        the config dict."""
        prof = ModelProfile(param_bytes=1 * 10 ** 9,
                            flops_per_step=1e15,
                            batch_tokens=8192, hidden=32768,
                            layer_count=128)
        seen = []

        def trial(cfg):
            seen.append(dict(cfg))
            return 1.0

        Planner(4, max_pp=4).plan_measured(prof, trial, top_k=2)
        assert any(c.get("pp_degree", 1) > 1 for c in seen)
        assert all("pp_schedule" in c for c in seen
                   if c.get("pp_degree", 1) > 1)

    def test_plan_measured_picks_trial_winner(self):
        """The measured phase must return the argmax of the trial
        throughputs, skipping failed trials (the reference's recorded
        OOM trials)."""
        prof = ModelProfile(param_bytes=10 * 2 ** 20,
                            flops_per_step=1e12, batch_tokens=2048,
                            hidden=256, layer_count=2)
        calls = []

        def trial(cfg):
            calls.append(tuple(sorted(cfg.items())))
            if cfg["dp_degree"] == 8:
                raise MemoryError("pretend OOM")
            return 100.0 * cfg["fsdp_degree"]  # fsdp-heaviest "wins"

        best = Planner(8).plan_measured(prof, trial, top_k=3)
        assert len(calls) == 3
        assert best.measured_items_per_s == max(
            100.0 * dict(c)["fsdp_degree"] for c in calls
            if dict(c)["dp_degree"] != 8)


class TestProfileModel:
    def test_profile_counts_params_and_layers(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        m = nn.Sequential(*[nn.Linear(32, 32) for _ in range(4)])
        prof = profile_model(m, batch_tokens=128)
        n_params = 4 * (32 * 32 + 32)
        assert prof.param_bytes == n_params * 4  # fp32
        assert prof.flops_per_step == 6.0 * n_params * 128
        assert prof.hidden == 32
        assert prof.layer_count == 4  # numbered sequential blocks


class TestEngineAutoPlan:
    def test_engine_plans_and_trains_llama(self):
        """Engine with strategy.auto and NO mesh: the planner must pick
        the known-best config for the tiny dryrun Llama on 8 virtual
        devices (pure dp — tiny model, comm-minimal), shard the model,
        and train (VERDICT item 6's done-gate)."""
        import jax

        from paddle_tpu.distributed.auto_parallel.engine import (Engine,
                                                                 Strategy)
        from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                       LlamaPretrainingCriterion)
        from paddle_tpu.models.llama import shard_llama

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        paddle.seed(0)
        cfg = LlamaConfig.tiny(use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        crit = LlamaPretrainingCriterion()
        strat = Strategy()
        strat.auto = {"enable": True,
                      "shard_fn": lambda m, mesh: shard_llama(
                          m, mesh, tp_axis="mp", fsdp_axis="fsdp")}
        eng = Engine(model, lambda lg, lb: crit(lg, lb), opt,
                     strategy=strat)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32)
        data = [(ids, ids)] * 3
        eng.fit(data, epochs=1)
        assert eng.plan_choice is not None
        # tiny model -> replication is comm-minimal: known best = dp=8
        assert eng.plan_choice.mesh_shape == (8, 1, 1), eng.plan_choice
        assert eng.mesh is not None
        assert np.isfinite(eng.history["loss"]).all()


class TestEnginePipelineRealization:
    """Planner v2 closes the loop: a pp plan is not just priced — the
    Engine EXECUTES it via the compiled GPipe schedule (ref: static
    engine + pipeline_scheduler_pass; segmentation contract =
    PipelineLayer's repeated-block family)."""

    def _model(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        blocks = [nn.Sequential(nn.Linear(64, 64), nn.Tanh())
                  for _ in range(4)]
        return nn.Sequential(*blocks, nn.Linear(64, 64))

    def test_detect_split(self):
        from paddle_tpu.distributed.auto_parallel.engine_pp import (
            detect_pipeline_split)
        m = self._model()
        pre, fam, post = detect_pipeline_split(m)
        assert len(pre) == 0 and len(fam) == 4 and len(post) == 1

    def test_pipeline_step_matches_flat_oracle(self):
        import jax

        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.auto_parallel.engine_pp import (
            PipelineTrainStep)

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        m = self._model()
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((32, 64)).astype(np.float32)
        y = rng.standard_normal((32, 64)).astype(np.float32)
        # flat full-batch oracle BEFORE any update
        oracle0 = float(((m(paddle.to_tensor(x))
                          - paddle.to_tensor(y)) ** 2).mean().numpy())
        step = PipelineTrainStep(
            m, lambda o, l: ((o - l) ** 2).mean(), opt, pp=4,
            n_devices=8)
        l0 = float(step(x, y))
        # GPipe micro-batch mean == full-batch mean for a mean loss
        np.testing.assert_allclose(l0, oracle0, rtol=1e-5)
        l1 = float(step(x, y))
        assert l1 < l0  # SGD actually updated the stacked params

    def test_engine_auto_plans_and_runs_pipeline(self):
        import jax

        from paddle_tpu.distributed.auto_parallel.engine import (
            Engine, Strategy)
        from paddle_tpu.distributed.auto_parallel.planner import Cluster

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        m = self._model()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        # activation-dominated geometry: a 2048x64 batch makes remat
        # checkpoints + the live working set the memory drivers; flat
        # meshes price ~46MB however factored, while pp=4 (layers split
        # across stages, one micro-batch in flight) prices ~14MB — a
        # 20MB budget forces the pipeline plan
        strat = Strategy()
        strat.auto = {"enable": True, "max_pp": 4,
                      "cluster": Cluster(hbm_bytes=20e6)}
        eng = Engine(m, lambda o, l: ((o - l) ** 2).mean(), opt,
                     strategy=strat)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2048, 64)).astype(np.float32)
        oracle0 = float(((m(paddle.to_tensor(x))
                          - paddle.to_tensor(x)) ** 2).mean().numpy())
        eng.fit([(x, x)] * 3, epochs=1)
        assert eng.plan_choice is not None and eng.plan_choice.pp > 1, \
            eng.plan_choice
        # the Engine's executor runs compiled GPipe, and the plan was
        # priced with that schedule's fill-drain bubble — no misreport
        assert eng.plan_choice.schedule == "gpipe"
        losses = eng.history["loss"]
        np.testing.assert_allclose(losses[0], oracle0, rtol=1e-4)
        assert losses[-1] < losses[0]
        # updates must WRITE BACK into the live model (evaluate/save
        # after a pipeline fit see trained weights)
        post = float(((m(paddle.to_tensor(x))
                       - paddle.to_tensor(x)) ** 2).mean().numpy())
        assert post < oracle0, (post, oracle0)
        # checkpoint contract: flat name->Tensor incl. optimizer slots
        sd = eng._step.state_dict()
        assert any("#moment" in k or "#" in k for k in sd)
        eng._step.set_state_dict(sd)  # identity roundtrip
        np.testing.assert_allclose(
            float(((m(paddle.to_tensor(x)) - paddle.to_tensor(x)) ** 2)
                  .mean().numpy()), post, rtol=1e-6)


class TestContextAndExpertAxes:
    """r5 (VERDICT r4 #8): the planner prices the repo's own
    above-parity features — ring-attention context parallelism and MoE
    expert parallelism — instead of being unable to recommend them."""

    def test_long_sequence_plans_cp(self):
        """ONE 32k-token sample: dp/fsdp cannot split a single sample,
        so only cp (ring attention) scales the data axis — the planner
        must find it."""
        prof = ModelProfile(
            param_bytes=500 * 2**20, flops_per_step=6.0 * 2.5e8 * 32768,
            batch_tokens=32768, hidden=2048, layer_count=8,
            seq_len=32768)
        p = Planner(8, max_cp=8, max_mp=8)
        best = p.plan(prof, top_k=1)[0]
        assert best.cp > 1, vars(best)
        assert best.dp == 1 and best.fsdp == 1  # one sample, no dp
        # and every dp/fsdp>1 candidate was rejected for the right reason
        priced = [p.price(c, prof) for c in p.candidates()]
        for c in priced:
            if c.dp * c.fsdp > 1:
                assert not c.feasible and "sample" in c.reason

    def test_cp_respects_flash_tile_floor(self):
        prof = ModelProfile(param_bytes=2**20, flops_per_step=1e12,
                            batch_tokens=512, hidden=256, layer_count=2,
                            seq_len=512)
        p = Planner(8, max_cp=8)
        priced = [p.price(c, prof) for c in p.candidates()]
        for c in priced:
            if c.cp > 4:  # 512/8 = 64 < 128-row flash tile
                assert not c.feasible and "flash tile" in c.reason

    def test_moe_model_plans_ep(self):
        """Expert-heavy MoE: sharding experts over ep costs one
        alltoall pair per MoE layer, vs fsdp's 3x full-param
        allgather/reduce-scatter — ep must win."""
        GB = 2**30
        prof = ModelProfile(
            param_bytes=int(8.2 * GB), flops_per_step=6.0 * 4.1e9 * 16384,
            batch_tokens=16384, hidden=4096, layer_count=4,
            moe_expert_param_bytes=8 * GB, moe_layer_count=4)
        # max_mp=1: MoE expert FFNs are ep-sharded, not tp-sharded
        # (the caller's shard_fn gates mp the same way Engine does)
        p = Planner(8, max_ep=8, max_mp=1)
        best = p.plan(prof, top_k=1)[0]
        assert best.ep > 1, vars(best)

    def test_ep_shards_expert_memory(self):
        """The ep axis divides EXPERT state only; a dense-param-only
        model gains nothing from ep (it still pays the dense grad
        allreduce) and the planner keeps ep=1."""
        GB = 2**30
        prof = ModelProfile(
            param_bytes=2 * GB, flops_per_step=6.0 * 1e9 * 16384,
            batch_tokens=16384, hidden=4096, layer_count=4,
            moe_expert_param_bytes=0, moe_layer_count=0)
        p = Planner(8, max_ep=8)
        best = p.plan(prof, top_k=1)[0]
        assert best.ep == 1, vars(best)
        # a dense model's ep>1 candidates are rejected, not free-ridden
        for c in [p.price(c, prof) for c in p.candidates()]:
            if c.ep > 1:
                assert not c.feasible and "no MoE" in c.reason
        # memory accounting: expert bytes divide by ep
        moe = ModelProfile(
            param_bytes=9 * GB, flops_per_step=1e15,
            batch_tokens=16384, hidden=4096, layer_count=4,
            moe_expert_param_bytes=8 * GB, moe_layer_count=4)
        c8 = p.price(PlanCandidate(dp=1, fsdp=1, mp=1, ep=8), moe)
        c1 = p.price(PlanCandidate(dp=8, fsdp=1, mp=1), moe)
        assert c8.est_mem_bytes < c1.est_mem_bytes


class TestClusterAutoDetect:
    """r5 (VERDICT r4 #10): the planner no longer needs a hand-filled
    cluster spec — detect_cluster builds one from jax.devices() +
    PJRT memory stats, with an optional measured probe (matmul peak,
    psum latency). Runs on whatever backend CI has."""

    def test_detect_without_probe(self):
        from paddle_tpu.distributed.auto_parallel.planner import (
            detect_cluster)
        c = detect_cluster()
        assert c.chip_flops > 0 and c.hbm_bytes > 0
        assert c.ici_bandwidth > 0 and c.ici_latency > 0

    def test_detect_with_probe_and_plan(self):
        from paddle_tpu.distributed.auto_parallel.planner import (
            detect_cluster)
        c = detect_cluster(probe=True)
        assert c.chip_flops > 1e9          # the probe measured SOMETHING
        prof = ModelProfile(param_bytes=2**24, flops_per_step=1e12,
                            batch_tokens=4096, hidden=512, layer_count=2)
        best = Planner(8, cluster=c).plan(prof, top_k=1)[0]
        assert best.feasible
