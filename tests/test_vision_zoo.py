"""Forward-shape tests for the extended vision model zoo
(ref: python/paddle/vision/models/__init__.py surface)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models as M


def _img(n=1, size=64):
    return paddle.to_tensor(
        np.random.default_rng(0).normal(size=(n, 3, size, size))
        .astype(np.float32))


def test_alexnet_forward():
    m = M.alexnet(num_classes=7)
    m.eval()
    assert m(_img(size=224)).shape == [1, 7]


def test_squeezenet_forward():
    m = M.squeezenet1_1(num_classes=6)
    m.eval()
    assert m(_img(size=96)).shape == [1, 6]


@pytest.mark.slow
def test_squeezenet10_forward():
    m = M.squeezenet1_0(num_classes=6)
    m.eval()
    assert m(_img(size=96)).shape == [1, 6]


def test_densenet_forward_backward():
    m = M.densenet121(num_classes=5)
    m.eval()
    x = _img(size=64)
    out = m(x)
    assert out.shape == [1, 5]
    # structure sanity: final feature width of densenet121 is 1024
    assert m.classifier.weight.shape[0] == 1024


def test_shufflenet_forward():
    m = M.shufflenet_v2_x0_25(num_classes=4)
    m.eval()
    assert m(_img(size=64)).shape == [1, 4]


def test_shufflenet_swish_forward():
    m = M.shufflenet_v2_swish(num_classes=4)
    m.eval()
    assert m(_img(size=64)).shape == [1, 4]


def test_mobilenet_v3_forward():
    m = M.mobilenet_v3_small(num_classes=3)
    m.eval()
    assert m(_img(size=64)).shape == [1, 3]


@pytest.mark.slow
def test_mobilenet_v3_large_forward():
    m = M.mobilenet_v3_large(num_classes=3)
    m.eval()
    assert m(_img(size=64)).shape == [1, 3]


def test_googlenet_forward_aux_heads():
    m = M.googlenet(num_classes=9)
    m.eval()
    out, aux1, aux2 = m(_img(size=128))
    assert out.shape == [1, 9]
    assert aux1.shape == [1, 9] and aux2.shape == [1, 9]


@pytest.mark.slow
def test_inception_v3_forward():
    m = M.inception_v3(num_classes=8)
    m.eval()
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(1, 3, 299, 299))
        .astype(np.float32))
    assert m(x).shape == [1, 8]


def test_pretrained_offline_fails_loudly(monkeypatch, tmp_path):
    """pretrained=True with no network and no local override must fail
    with the override instructions, not hang or silently random-init
    (the full machinery incl. local-dir round-trip is in
    tests/test_pretrained.py)."""
    import importlib

    import paddle_tpu.utils.download as dl
    # attribute access resolves to the constructor functions (package
    # __init__ shadowing); import_module gets the module objects
    an = importlib.import_module("paddle_tpu.vision.models.alexnet")
    dn = importlib.import_module("paddle_tpu.vision.models.densenet")
    monkeypatch.delenv("PADDLE_TPU_PRETRAINED_DIR", raising=False)
    monkeypatch.setattr(dl, "WEIGHTS_HOME", str(tmp_path))
    # unresolvable host: the test must not depend on (or pay for) real
    # egress on machines that have it
    monkeypatch.setitem(an.model_urls, "alexnet",
                        ("https://invalid.example.invalid/a.pdparams",
                         None))
    monkeypatch.setitem(dn.model_urls, "densenet121",
                        ("https://invalid.example.invalid/d.pdparams",
                         None))
    with pytest.raises(RuntimeError, match="PADDLE_TPU_PRETRAINED_DIR"):
        M.alexnet(pretrained=True)
    with pytest.raises(RuntimeError, match="PADDLE_TPU_PRETRAINED_DIR"):
        M.densenet121(pretrained=True)


@pytest.mark.slow  # ~87s: a full densenet121 fwd+bwd+step compile on CPU
def test_densenet_train_step_decreases_loss():
    """End-to-end: one tiny training step works through BN/dense blocks."""
    m = M.densenet121(num_classes=2)
    m.train()
    opt = paddle.optimizer.SGD(learning_rate=1e-3,
                               parameters=m.parameters())
    x = _img(n=2, size=32)
    labels = paddle.to_tensor(np.array([0, 1], np.int64))
    ce = paddle.nn.CrossEntropyLoss()
    losses = []
    for _ in range(2):
        loss = ce(m(x), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert np.isfinite(losses).all()


def test_resnext_variants_forward():
    # all six reference resnext factories exist; spot-run the smallest
    for name in ("resnext50_32x4d", "resnext50_64x4d",
                 "resnext101_32x4d", "resnext101_64x4d",
                 "resnext152_32x4d", "resnext152_64x4d"):
        assert hasattr(M, name), name
    m = M.resnext50_32x4d(num_classes=4)
    out = m(_img())
    assert out.shape == [1, 4]


def test_models_all_matches_reference_surface():
    import ast
    ref = ("/root/reference/python/paddle/vision/models/__init__.py")
    import os
    if not os.path.exists(ref):
        pytest.skip("reference tree unavailable")
    tree = ast.parse(open(ref).read())
    ref_all = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", None) == "__all__":
                    ref_all = [ast.literal_eval(e)
                               for e in node.value.elts]
    assert ref_all, "no __all__ found in reference"
    missing = [n for n in ref_all if n not in M.__all__]
    assert missing == [], f"vision.models missing: {missing}"
