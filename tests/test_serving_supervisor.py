"""Self-healing serving plane (ISSUE 15): supervised decode loop with
crash recovery, adaptive admission, and canary rollout.

Chaos contract pinned here: with a KillPoint crashing the decode loop
mid-decode under concurrent submits, the supervisor restarts the loop
and every accepted request ends with exactly ONE terminal flight
event; recovered greedy streams are BIT-equal to an uninterrupted
oracle (committed tokens are durable host state — recovery re-prefills
``prompt + committed`` through the normal admission path); a request
active at two consecutive crashes is quarantined (reason=poison)
instead of crash-looping the replica; the adaptive policy brownouts
(spec window, then prefill chunk) BEFORE any hard shed and releases
when pressure clears; and a divergent checkpoint rolled onto a canary
is auto-rolled-back bit-equal while the rollout halts.

Cost discipline: the oracle streams are memoized on a module-scoped
dense engine, most chaos mechanics run on jax-free fake engines (the
test_flight FakeEngine pattern, made causal-LM-faithful: the next
token is a pure function of the WHOLE sequence so far, so re-prefill
resumes exactly like the real engines), and only the bit-equality
chaos test and the rollout test touch compiled engines.
"""
from __future__ import annotations

import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability import flight
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (GenerationServer, LlamaDecodeEngine,
                                PagedLlamaDecodeEngine)
from paddle_tpu.serving_cache import PagedKVCache
from paddle_tpu.serving_supervisor import (AdaptiveAdmissionPolicy,
                                           RolloutPolicy,
                                           ServingSupervisor,
                                           StaticShedPolicy,
                                           default_policy, rollout,
                                           supervise)
from paddle_tpu.utils import fault_injection as fi

CFG = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=2, use_flash_attention=False)

TERMINAL = {"finished", "expired", "failed"}


def _reg():
    return obs.default_registry()


@pytest.fixture(scope="module")
def model():
    paddle.seed(11)
    return LlamaForCausalLM(LlamaConfig.tiny(**CFG))


@pytest.fixture(scope="module")
def model_b():
    paddle.seed(23)
    return LlamaForCausalLM(LlamaConfig.tiny(**CFG))


@pytest.fixture(scope="module")
def dense_ref(model):
    """Memoized greedy oracle streams (the uninterrupted reference)."""
    eng = LlamaDecodeEngine(model, max_slots=1, max_seq=64)
    cache = {}

    def ref(prompt, n_new):
        key = (tuple(int(t) for t in prompt), int(n_new))
        if key not in cache:
            cache[key] = eng.generate(list(key[0]), max_new_tokens=n_new)
        return cache[key]

    return ref


@pytest.fixture(scope="module")
def paged64(model):
    """Shared paged engine; tests reset it to pristine afterwards."""
    return PagedLlamaDecodeEngine(model, max_slots=2, max_seq=64,
                                  block_size=8, prefill_chunk=8)


@pytest.fixture()
def dump_dir(tmp_path):
    prev = paddle.get_flags("FLAGS_flight_dump_dir")
    paddle.set_flags({"FLAGS_flight_dump_dir": str(tmp_path)})
    try:
        yield str(tmp_path)
    finally:
        paddle.set_flags(prev)


@pytest.fixture(autouse=True)
def quiet_thread_hook():
    """The seeded KillPoints die through threading.excepthook; keep the
    default traceback spew out of the test log."""
    prev = threading.excepthook
    threading.excepthook = lambda args: None
    try:
        yield
    finally:
        threading.excepthook = prev
        fi.clear()


class FakeCausalEngine:
    """jax-free duck-typed engine whose next token is a pure function
    of the WHOLE token sequence so far — prefill(prompt + committed)
    therefore resumes exactly like the real causal engines, which is
    the property crash recovery leans on."""

    def __init__(self, slots=2, max_seq=64, step_sleep=0.0):
        self.max_slots, self.max_seq, self.eos_id = slots, max_seq, None
        self.step_sleep = step_sleep
        self.active = np.zeros(slots, bool)
        self.pos = np.zeros(slots, np.int64)
        self._seq = {}

    @staticmethod
    def _next(seq):
        return (sum(seq) * 7 + len(seq)) % 997

    def prefill(self, slot, prompt):
        seq = [int(t) for t in np.asarray(prompt).reshape(-1)]
        tok = self._next(seq)
        self._seq[slot] = seq + [tok]
        self.pos[slot] = len(self._seq[slot])
        self.active[slot] = True
        return tok

    def step(self):
        if self.step_sleep:
            time.sleep(self.step_sleep)
        out = np.zeros(self.max_slots, np.int64)
        for s in range(self.max_slots):
            if self.active[s]:
                tok = self._next(self._seq[s])
                self._seq[s].append(tok)
                self.pos[s] += 1
                out[s] = tok
        return out

    def release(self, slot, evicted=False):
        self.active[slot] = False
        self.pos[slot] = 0
        self._seq.pop(slot, None)

    def reset_state(self):
        self.active[:] = False
        self.pos[:] = 0
        self._seq.clear()


class FakePagedEngine(FakeCausalEngine):
    """The causal fake over a REAL PagedKVCache (pure host), so the
    adaptive-admission evidence (blocks_free/reservations) and the
    paged server path (begin_request/prefill_chunk/defer) are all
    genuine — without a single compile."""

    paged = True

    def __init__(self, slots=2, max_seq=64, block_size=8, num_blocks=8,
                 step_sleep=0.0):
        super().__init__(slots=slots, max_seq=max_seq,
                         step_sleep=step_sleep)
        self._kv = PagedKVCache(max_slots=slots, max_seq=max_seq,
                                block_size=block_size,
                                num_blocks=num_blocks)
        self._prefill_state = {}
        self._spec_suppressed = False
        self._chunk_cap = None

    def spec_ready(self):
        return False  # no draft on the fake

    def begin_request(self, slot, prompt, budget):
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        total = min(len(prompt) + max(int(budget), 1), self.max_seq)
        if not self._kv.admit(slot, len(prompt), total):
            return False
        self._prefill_state[slot] = prompt
        self.active[slot] = False
        return True

    def prefill_chunk(self, slot):
        seq = self._prefill_state.pop(slot)
        tok = self._next(seq)
        self._seq[slot] = seq + [tok]
        self.pos[slot] = len(seq)
        self.active[slot] = True
        return tok

    def step(self):
        for s in range(self.max_slots):
            if self.active[s]:
                self._kv.ensure_token(s, int(self.pos[s]))
        return super().step()

    def release(self, slot, evicted=False):
        super().release(slot, evicted=evicted)
        self._prefill_state.pop(slot, None)
        self._kv.release(slot, evicted=evicted)

    def reset_state(self):
        for s in range(self.max_slots):
            self._kv.release(s, evicted=True)
        self._prefill_state.clear()
        super().reset_state()


def _terminal_counts(trace_ids):
    evs = flight.events(category="serving")
    return {tid: sum(1 for e in evs
                     if e.get("trace_id") == tid
                     and e["name"] in TERMINAL)
            for tid in trace_ids}


# ---------------------------------------------------------------------------
# crash recovery
# ---------------------------------------------------------------------------

class TestCrashRecovery:
    def test_chaos_killpoint_recovers_bit_equal(self, model, dense_ref,
                                                paged64, dump_dir):
        """The acceptance chaos scenario on the REAL paged engine:
        KillPoint mid-decode under concurrent submits — the supervisor
        dumps, restarts, and every stream finishes BIT-equal to the
        uninterrupted oracle with exactly one terminal flight event."""
        flight.clear()
        srv = GenerationServer(paged64)
        sup = supervise(srv, backoff=0.01)
        reqs = []
        try:
            # the 3rd decode passage dies: victims are mid-stream with
            # committed tokens (and, with 2 slots x 3 requests, one
            # request is still queued — untouched by the crash)
            fi.inject("serving.decode", kill=True, skip=2)
            for prompt, n in (([5, 9, 11], 7), ([2, 4], 6),
                              ([7, 1, 3, 8], 5)):
                reqs.append((srv.submit(prompt, max_new_tokens=n),
                             prompt, n))
            for req, prompt, n in reqs:
                assert req["done"].wait(60), srv.stats()
                assert req["error"] is None
                assert list(req["out"]) == dense_ref(prompt, n)
            assert sup.restarts == 1
            assert sup.recovered >= 1 and sup.quarantined == 0
            counts = _terminal_counts([r["trace_id"]
                                       for r, _, _ in reqs])
            assert all(c == 1 for c in counts.values()), counts
            # the supervisor journaled the death + recovery + restart
            names = [e["name"]
                     for e in flight.events(category="supervisor")]
            assert "loop_death" in names and "restart" in names
            assert "recover" in names
            # and auto-dumped forensics
            assert flight.find_dumps(dump_dir)
            # the replica is healthy: pool pristine, a fresh request
            # serves the oracle stream
            assert srv.generate([6, 2], max_new_tokens=4,
                                timeout=60) == dense_ref([6, 2], 4)
        finally:
            fi.clear("serving.decode")
            sup.stop()
            srv.shutdown(timeout=10)
            paged64.reset_state()
        st = paged64._kv.stats()
        assert st["blocks_used"] == 0 and st["blocks_reserved"] == 0

    def test_quarantine_repeat_offender(self, dump_dir):
        """A request active at two consecutive crashes is failed
        (reason=poison) instead of re-admitted a third time; the loop
        stays up for everyone else."""
        flight.clear()
        srv = GenerationServer(FakeCausalEngine())
        sup = supervise(srv, backoff=0.01, quarantine_after=2)
        try:
            fi.inject("serving.decode", kill=True, times=2, skip=1)
            req = srv.submit([5, 6], max_new_tokens=20)
            assert req["done"].wait(30)
            assert isinstance(req["error"], RuntimeError)
            assert "poison" in str(req["error"])
            # the quarantine verdict lands BEFORE the backoff+restart;
            # give the second restart its beat to complete
            deadline = time.monotonic() + 10
            while sup.restarts < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sup.quarantined == 1 and sup.restarts == 2
            quar = [e for e in flight.events(category="supervisor")
                    if e["name"] == "quarantine"]
            assert quar and quar[-1]["attrs"]["reason"] == "poison"
            assert quar[-1]["trace_id"] == req["trace_id"]
            # exactly ONE terminal event, and it is the failure
            assert _terminal_counts([req["trace_id"]]) \
                == {req["trace_id"]: 1}
            assert srv.stats()["quarantined"] == 1
            # the replica survives its poison input
            assert len(srv.generate([7], max_new_tokens=3,
                                    timeout=30)) == 3
        finally:
            sup.stop()
            srv.shutdown(timeout=10)

    def test_backoff_grows_and_gives_up(self, dump_dir):
        """Every decode passage dies: restarts back off exponentially
        and the supervisor eventually fails everything pending instead
        of spinning forever."""
        flight.clear()
        srv = GenerationServer(FakeCausalEngine())
        sup = supervise(srv, backoff=0.005, backoff_cap=0.02,
                        max_restarts=3, quarantine_after=99)
        try:
            fi.inject("serving.decode", kill=True, times=100)
            req = srv.submit([3], max_new_tokens=5)
            assert req["done"].wait(30)
            assert isinstance(req["error"], RuntimeError)
            assert "gave up" in str(req["error"])
            assert sup.gave_up and sup.restarts == 3
            assert any(e["name"] == "give_up"
                       for e in flight.events(category="supervisor"))
            # a given-up server stops its intake: later submissions
            # reject FAST instead of queueing for a loop that will
            # never drain them, and shutdown returns immediately
            with pytest.raises(RuntimeError, match="shutting down"):
                srv.submit([1], max_new_tokens=2)
            assert srv.shutdown(timeout=5)
        finally:
            fi.clear("serving.decode")
            sup.stop()

    def test_double_recovery_stays_bit_equal(self, dump_dir):
        """With a quarantine threshold above 2, a request recovered
        TWICE must still resume bit-equal — only the not-yet-folded
        committed tokens join the prompt at each recovery (re-folding
        would duplicate the stream)."""
        srv = GenerationServer(FakeCausalEngine())
        sup = supervise(srv, backoff=0.01, quarantine_after=3)
        try:
            # two kills from one arm: passages 1-2 clean (tokens
            # commit), passage 3 dies, and the recovered loop's first
            # decode passage dies again — so recovery #2 must fold
            # ONLY the tokens committed since recovery #1
            fi.inject("serving.decode", kill=True, times=2, skip=2)
            req = srv.submit([8, 3], max_new_tokens=10)
            assert req["done"].wait(30)
            assert req["error"] is None
            oracle = GenerationServer(FakeCausalEngine())
            want = oracle.generate([8, 3], max_new_tokens=10,
                                   timeout=30)
            oracle.shutdown()
            assert list(req["out"]) == want
            assert sup.restarts == 2 and sup.quarantined == 0
        finally:
            fi.clear("serving.decode")
            sup.stop()
            srv.shutdown(timeout=10)

    def test_stall_watchdog_fences_and_recovers(self, dump_dir):
        """A decode loop that is alive but wedged (heartbeat stale
        while holding work) is fenced and replaced; the wedged zombie
        exits through the epoch fence when it finally wakes, and the
        request resumes bit-equal."""
        flight.clear()

        class StallEngine(FakeCausalEngine):
            def __init__(self):
                super().__init__()
                self.gate = threading.Event()
                self.calls = 0

            def step(self):
                self.calls += 1
                if self.calls == 3:
                    self.gate.wait(30)  # the stall (zombie parks here)
                return super().step()

        eng = StallEngine()
        srv = GenerationServer(eng)
        sup = supervise(srv, backoff=0.01, stall_seconds=0.15,
                        poll=0.02)
        try:
            req = srv.submit([4, 2], max_new_tokens=8)
            assert req["done"].wait(30)
            assert req["error"] is None
            oracle = GenerationServer(FakeCausalEngine())
            want = oracle.generate([4, 2], max_new_tokens=8, timeout=30)
            oracle.shutdown()
            assert list(req["out"]) == want
            assert sup.stalls == 1 and sup.restarts == 1
            assert srv.stats()["loop_restarts"] == 1
        finally:
            eng.gate.set()  # release the zombie; the fence retires it
            sup.stop()
            srv.shutdown(timeout=10)

    def test_gauges_true_after_unsupervised_crash(self, model, paged64):
        """Satellite audit pin: after a KillPoint kills the loop with
        NO supervisor attached, queue_depth/in_flight/blocks_used must
        read the TRUE wreckage (the victim still holds its slot and
        blocks) — not whatever the last completed step boundary wrote
        (the kill lands between admission and the gauge sweep)."""
        flight.clear()
        srv = GenerationServer(paged64)
        try:
            fi.inject("serving.decode", kill=True)  # first passage
            req = srv.submit([9, 8, 7], max_new_tokens=6)
            deadline = time.monotonic() + 30
            while srv._thread.is_alive() \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not srv._thread.is_alive()
            assert srv.stats()["crashed"] == 1
            assert not req["done"].is_set()  # died mid-flight, no
            # terminal event before recovery (none is coming)
            g = _reg()
            assert g.get("serving.in_flight").value() == 1.0
            assert g.get("serving.queue_depth").value() == 0.0
            assert g.get("serving.blocks_used").value() > 0
            crashes = [e for e in flight.events(category="serving")
                       if e["name"] == "loop_crashed"]
            assert crashes \
                and crashes[-1]["attrs"]["error"] == "KillPoint"
        finally:
            fi.clear("serving.decode")
            srv.shutdown(drain=False, timeout=0.5)
            paged64.reset_state()


# ---------------------------------------------------------------------------
# adaptive admission
# ---------------------------------------------------------------------------

class TestAdaptiveAdmission:
    def test_default_policy_follows_flag(self):
        assert isinstance(default_policy(), StaticShedPolicy)
        paddle.set_flags(
            {"FLAGS_serving_admission_policy": "adaptive"})
        try:
            assert isinstance(default_policy(),
                              AdaptiveAdmissionPolicy)
        finally:
            paddle.set_flags(
                {"FLAGS_serving_admission_policy": "static"})

    def test_brownout_staircase_before_shed_and_release(self):
        """Integration under synthetic block starvation + queue
        growth (real PagedKVCache accounting, fake compute): the
        journal shows spec brownout, then prefill brownout, then — and
        only then — a hard shed; counted; and admission releases once
        pressure clears."""
        flight.clear()
        policy = AdaptiveAdmissionPolicy(alpha=0.9, starve_frac=0.4,
                                         queue_bound=1)
        # pool of 8 blocks: the first request reserves 6, leaving 2
        # (starved at the 0.4 threshold but NOT exhausted — shedding
        # engages before the pool runs dry), the second defers, the
        # rest queue behind it
        eng = FakePagedEngine(num_blocks=8, step_sleep=0.002)
        srv = GenerationServer(eng, policy=policy)
        try:
            a = srv.submit([1, 2, 3, 4], max_new_tokens=40)
            b = srv.submit([5, 6, 7, 8], max_new_tokens=40)
            c = srv.submit([9], max_new_tokens=3)
            # pressure builds one level per step boundary
            deadline = time.monotonic() + 30
            while policy.level < 3 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert policy.level == 3, policy.journal()
            assert eng._spec_suppressed and eng._chunk_cap == 8
            shed0 = srv.stats()["shed"]
            with pytest.raises(RuntimeError, match="shed"):
                srv.submit([4], max_new_tokens=2)
            assert srv.stats()["shed"] == shed0 + 1
            events = [j["event"] for j in policy.journal()]
            assert "shed" in events
            order = [events.index("engage_brownout_spec"),
                     events.index("engage_brownout_prefill"),
                     events.index("engage_shed")]
            assert order == sorted(order), events
            # brownout engaged strictly before the hard rejection
            assert events.index("engage_brownout_spec") \
                < events.index("shed")
            assert [e for e in flight.events(category="admission")]
            # drain: once every stream completes and the pool clears,
            # admission releases and a fresh request is served
            for req in (a, b, c):
                assert req["done"].wait(60)
                assert req["error"] is None
            out = srv.generate([3, 3], max_new_tokens=2, timeout=30)
            assert len(out) == 2
            assert policy.level == 0
            assert any(e.startswith("release_")
                       for e in [j["event"] for j in policy.journal()])
            assert not eng._spec_suppressed and eng._chunk_cap is None
        finally:
            srv.shutdown(timeout=10)

    def test_deadline_aware_rejection_at_submit(self):
        """A request whose deadline cannot be met at the observed
        steps/sec is rejected at SUBMIT (counted + journaled), before
        it burns blocks; a meetable one is admitted."""
        flight.clear()
        policy = AdaptiveAdmissionPolicy(alpha=0.9, min_steps=3)
        eng = FakePagedEngine(num_blocks=32, step_sleep=0.02)
        srv = GenerationServer(eng, policy=policy)
        try:
            # warm the throughput EWMA with a real stream (~50 tok/s
            # per request at the fake's 0.02s step)
            srv.generate([1, 2], max_new_tokens=8, timeout=30)
            assert policy._ewma_rps is not None
            r0 = _reg().get(
                "serving.admission_deadline_rejected_total").value()
            with pytest.raises(RuntimeError, match="deadline"):
                srv.submit([1], max_new_tokens=10_000, deadline=0.5)
            assert _reg().get(
                "serving.admission_deadline_rejected_total").value() \
                == r0 + 1
            assert srv.stats()["deadline_rejected"] == 1
            assert any(j["event"] == "deadline_reject"
                       for j in policy.journal())
            # plenty of deadline: admitted and served
            out = srv.generate([1], max_new_tokens=2, timeout=30,
                               deadline=60.0)
            assert len(out) == 2
        finally:
            srv.shutdown(timeout=10)

    def test_static_policy_unchanged_behavior(self):
        """The default policy is the static flag rule: no brownout
        state, no deadline rejection — deadline-bound requests expire
        (post-admission) exactly as before."""
        srv = GenerationServer(FakeCausalEngine(step_sleep=0.01))
        try:
            assert isinstance(srv.policy, StaticShedPolicy)
            req = srv.submit([1], max_new_tokens=1000, deadline=0.05)
            assert req["done"].wait(30)
            assert isinstance(req["error"], TimeoutError)
        finally:
            srv.shutdown(timeout=10)


# ---------------------------------------------------------------------------
# canary rollout
# ---------------------------------------------------------------------------

class TestCanaryRollout:
    @staticmethod
    def _fleet(model, n=2):
        servers = []
        for _ in range(n):
            eng = PagedLlamaDecodeEngine(model, max_slots=1,
                                         max_seq=64, block_size=8,
                                         prefill_chunk=8)
            servers.append(GenerationServer(eng))
        return servers

    @staticmethod
    def _sd(model):
        return {k: v for k, v in model.named_parameters()}

    def test_good_checkpoint_rolls_everywhere_and_bad_rolls_back(
            self, model, model_b):
        """One fleet, three deploys: identical weights proceed across
        every replica (zero probe divergence); a divergent checkpoint
        trips the canary probe and is auto-rolled-back BIT-equal with
        the rollout halted (replica 2 never touched); a NaN-poisoned
        checkpoint is stopped by the finite-weights gate before ANY
        replica swaps."""
        flight.clear()
        servers = self._fleet(model)
        pol = RolloutPolicy(probe_prompt=[1, 2, 3], probe_tokens=5,
                            max_divergence=0.0)
        try:
            baseline = servers[0].generate([1, 2, 3], 5, timeout=60)
            # -- good: same weights, divergence 0, full fleet
            rep = rollout(self._sd(model), servers, pol)
            assert rep["swapped"] == 2 and not rep["halted"]
            assert rep["stages"][0]["divergence"] == 0.0
            assert servers[0].stats()["weight_swaps"] == 1
            # -- divergent: canary rolls back, fleet untouched
            before_1 = servers[1].engine.params
            rolled = _reg().get(
                "serving.rollout_rollbacks_total").value()
            rep = rollout(self._sd(model_b), servers, pol)
            assert rep["halted"] and rep["rolled_back"] == 1
            assert rep["reason"] == "probe_divergence"
            assert rep["stages"][0]["divergence"] > 0.0
            assert servers[1].engine.params is before_1
            assert _reg().get(
                "serving.rollout_rollbacks_total").value() \
                == rolled + 1
            # pre-swap streams restored bit-equal on the canary
            assert servers[0].generate([1, 2, 3], 5,
                                       timeout=60) == baseline
            names = [e["name"]
                     for e in flight.events(category="rollout")]
            assert "canary_probe" in names and "rollback" in names
            # -- NaN: the finite gate halts before any swap
            sd = self._sd(model)
            bad = {k: (v * float("nan") if k == "llama.norm.weight"
                       else v) for k, v in sd.items()}
            nf0 = _reg().get(
                "serving.rollout_nonfinite_weights_total").value()
            rep = rollout(bad, servers, pol)
            assert rep["halted"] and rep["swapped"] == 0
            assert rep["reason"] == "nonfinite_weights"
            assert _reg().get(
                "serving.rollout_nonfinite_weights_total").value() \
                > nf0
            assert servers[0].generate([1, 2, 3], 5,
                                       timeout=60) == baseline
        finally:
            for srv in servers:
                srv.shutdown(timeout=10)
