"""Distributed whole-step capture (ISSUE 13): AMP/GradScaler steps and
DistTrainStep run through the SOT capture engine, with bucketed
compute–collective overlap.

Pins:

- an AMP/GradScaler ``Model.fit``-style step runs as ONE donated
  captured executable: the dynamic audit reports ZERO host syncs and
  exactly one executable call in steady state, and
  ``sot.fallbacks_total{reason=amp}`` stays 0 (the PR 10 residue,
  asserted extinct — the reason label no longer exists);
- captured-vs-eager equality for AMP steps, including a forced
  non-finite skip step: the scaler plane (scale value, good/bad
  counters, skip decision) is BIT-equal, loss/params equal at the
  f32-ulp fusion-rounding bound the PR 10 kill-switch test pinned
  (per-op eager XLA vs one whole program round differently in the
  last bit; bf16 autocast widens that to bf16 epsilon);
- ``DistTrainStep`` routes through ``CapturedStep`` (its bespoke
  ``jax.jit`` closure is GONE): shared compile/cache-hit counters,
  signature-change retrace, checkpoint restore -> continue identical
  under both kill-switch settings;
- bucketed gradient sync: assignment unit laws (every grad in exactly
  one bucket, reverse-backward order preserved, byte target
  respected), the captured distributed program carries >= 2 buckets
  whose collectives are pinned in the jaxpr (optimization_barrier
  chain + sharding_constraint nodes) and the HLO, the FIRST bucket's
  sync depends on only a fraction of the backward's dot_generals
  (the DAG independence that lets XLA's async collectives overlap
  remaining backward compute — the T3 structure), per-bucket flight
  events journal each step, and bucketing on/off is numerically
  identical.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import analysis
from paddle_tpu.hapi import Model
from paddle_tpu.observability import flight
from paddle_tpu.observability import metrics as om


def _toy_data(n=32, din=4, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, din)).astype(np.float32)
    W = rng.normal(size=(din, classes)).astype(np.float32)
    y = (X @ W).argmax(-1).astype(np.int64)
    return X, y


def _amp_model(**scaler_kw):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 3))
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.Adam(
        learning_rate=0.01, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        amp_configs={"level": "O1", "init_loss_scaling": 1024.0,
                     **scaler_kw})
    return m


class TestAmpCapture:
    def test_amp_step_captures_with_zero_fallbacks(self):
        X, y = _toy_data()
        m = _amp_model()
        for i in range(6):
            sl = slice((i * 8) % 32, (i * 8) % 32 + 8)
            float(m.train_batch([X[sl]], [y[sl]])[0])
        eng = m._captured
        # strict compile policy: sighting -> compile -> hits
        assert eng.stats["eager_steps"] == 1
        assert eng.stats["compiles"] == 1
        assert eng.stats["captured_steps"] == 5
        assert eng.stats["fallbacks"] == {}, eng.stats
        # the PR 10 residue is EXTINCT: no amp fallback reason exists
        cell = om.default_registry().get("sot.fallbacks_total")
        assert cell.value(reason="amp") == 0

    def test_captured_amp_step_audits_dispatch_free(self):
        """The acceptance pin: a steady-state AMP/GradScaler train
        step is ONE executable call with ZERO host syncs — the skip
        decision, the scale bookkeeping and the loss all stay on
        device (the loss fetches at the log boundary)."""
        X, y = _toy_data()
        m = _amp_model()
        for _ in range(3):
            m.train_batch([X[:8]], [y[:8]])

        def step():
            m.train_batch([X[:8]], [y[:8]])

        rep = analysis.audit(step, warmup=2)
        assert rep.syncs == [], rep.syncs
        before = dict(om.snapshot().get("sot", {}))
        m.train_batch([X[:8]], [y[:8]])
        after = dict(om.snapshot().get("sot", {}))
        assert after["captured_steps_total"] - \
            before["captured_steps_total"] == 1

    def test_captured_matches_eager_with_nonfinite_skip(self):
        """Captured vs FLAGS_sot_capture=0 eager, same 7-step stream
        with one poisoned batch at step 4: the scaler plane is
        BIT-equal (scale halves exactly once, the poisoned update is
        skipped on both paths), losses/weights agree at the bf16
        fusion-rounding bound."""
        X, y = _toy_data()
        X_bad = X[:8].copy()
        X_bad[0, 0] = np.inf

        def run(m):
            scales, losses, snaps = [], [], []
            for i in range(7):
                xb = X_bad if i == 4 else X[(i * 8) % 32:
                                            (i * 8) % 32 + 8]
                yb = y[:8] if i == 4 else y[(i * 8) % 32:
                                            (i * 8) % 32 + 8]
                losses.append(float(m.train_batch([xb], [yb])[0]))
                scales.append(float(m._scaler.get_loss_scaling()))
                snaps.append(m.network[0].weight.numpy().copy())
            return scales, losses, snaps

        m_cap = _amp_model(decr_every_n_nan_or_inf=1)
        s_cap, l_cap, w_cap = run(m_cap)
        assert m_cap._captured.stats["fallbacks"] == {}
        assert m_cap._captured.stats["captured_steps"] >= 5
        # the poisoned step: update skipped, scale halved (bit-exact —
        # powers of two), training resumes on the next step
        assert s_cap[3] == 1024.0 and s_cap[4] == 512.0, s_cap
        np.testing.assert_array_equal(w_cap[4], w_cap[3])
        assert not np.array_equal(w_cap[5], w_cap[4])

        paddle.set_flags({"FLAGS_sot_capture": 0})
        try:
            m_off = _amp_model(decr_every_n_nan_or_inf=1)
            s_off, l_off, w_off = run(m_off)
            assert m_off._captured.stats["captured_steps"] == 0
        finally:
            paddle.set_flags({"FLAGS_sot_capture": 1})
        # scaler state: bit-equal across the whole stream
        np.testing.assert_array_equal(np.array(s_cap), np.array(s_off))
        np.testing.assert_allclose(np.array(l_cap), np.array(l_off),
                                   rtol=2e-3)
        np.testing.assert_allclose(w_cap[-1], w_off[-1], rtol=2e-3,
                                   atol=1e-4)

    def test_f32_amp_matches_eager_at_ulp(self):
        """With matmul/linear black-listed (pure-f32 numerics) the
        captured scaler iteration reproduces eager at the same
        one-ulp bound the plain captured step has — the scaler
        fold-in itself adds NOTHING."""
        X, y = _toy_data()

        def build():
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(),
                                nn.Linear(16, 3))
            m = Model(net)
            m.prepare(optimizer=paddle.optimizer.Adam(
                learning_rate=0.01, parameters=net.parameters()),
                loss=nn.CrossEntropyLoss(),
                amp_configs={"level": "O1",
                             "init_loss_scaling": 1024.0,
                             "custom_black_list": ["matmul", "linear"]})
            return m

        def run(m):
            return [float(m.train_batch(
                [X[(i * 8) % 32:(i * 8) % 32 + 8]],
                [y[(i * 8) % 32:(i * 8) % 32 + 8]])[0])
                for i in range(6)]

        caps = run(build())
        paddle.set_flags({"FLAGS_sot_capture": 0})
        try:
            offs = run(build())
        finally:
            paddle.set_flags({"FLAGS_sot_capture": 1})
        np.testing.assert_allclose(caps, offs, rtol=1e-6, atol=1e-7)

    def test_custom_scaler_step_falls_back_counted(self):
        """An instance-patched scaler (the shard_scaler wrap pattern)
        cannot capture: the step falls back eagerly with a counted
        ``scaler`` reason and the patched hook actually runs."""
        X, y = _toy_data()
        m = _amp_model()
        calls = []
        orig = m._scaler.unscale_
        m._scaler.unscale_ = lambda o: (calls.append(1), orig(o))[1]
        for _ in range(3):
            float(m.train_batch([X[:8]], [y[:8]])[0])
        assert calls, "the patched unscale_ must run (eager path)"
        assert m._captured.stats["fallbacks"].get("scaler", 0) >= 1
        assert m._captured.stats["captured_steps"] == 0


@pytest.fixture
def fsdp_llama():
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed import ProcessMesh
    from paddle_tpu.distributed.dist_train import DistTrainStep
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaPretrainingCriterion,
                                   shard_llama)

    mesh = ProcessMesh(np.arange(8), dim_names=["fsdp"])
    crit = LlamaPretrainingCriterion()

    def build(seed=0, **kw):
        paddle.seed(seed)
        # as small as a sharded llama gets: the file's cost is the
        # 8-virtual-device SPMD steps (every ZeRO-3 param pays
        # all-gather + reduce-scatter rendezvous per step, ~20ms each
        # on the single-core CI host), and tier-1 has an 870s budget —
        # ONE hidden layer keeps the collective count down while still
        # giving >= 2 grad buckets and a multi-dot backward
        cfg = LlamaConfig.tiny(
            num_hidden_layers=1, hidden_size=16, intermediate_size=32,
            num_attention_heads=2, num_key_value_heads=2,
            vocab_size=64, use_flash_attention=False)
        m = LlamaForCausalLM(cfg)
        shard_llama(m, mesh, tp_axis=None, fsdp_axis="fsdp")
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        step = DistTrainStep(
            m, lambda lg, lb: crit(lg, lb), opt,
            data_sharding=NamedSharding(mesh.to_jax_mesh(),
                                        P("fsdp", None)), **kw)
        return m, step

    ids = np.random.default_rng(0).integers(
        0, 64, (8, 16)).astype(np.int32)
    return build, ids


class TestDistCapturedStep:
    def test_dist_step_routes_through_captured_step(self, fsdp_llama):
        from paddle_tpu.jit.sot import CapturedStep
        build, ids = fsdp_llama
        _, step = build()
        # the bespoke jax.jit closure is GONE: the engine IS a
        # CapturedStep (non-strict), sharing guards/cache/telemetry
        assert isinstance(step._step, CapturedStep)
        assert not hasattr(step, "_jitted")
        before = dict(om.snapshot().get("sot", {}))
        losses = [float(step(ids, ids)) for _ in range(3)]
        after = dict(om.snapshot().get("sot", {}))
        assert step.stats["compiles"] == 1
        assert step.stats["captured_steps"] == 3
        assert step.stats["cache_hits"] == 2
        assert after["captured_steps_total"] - \
            before["captured_steps_total"] == 3
        assert losses[-1] < losses[0] + 1.0
        # signature-change retrace on the SAME engine: a new batch
        # shape is a guard miss — retrace, old program stays cached
        float(step(ids[:, :8], ids[:, :8]))
        assert step.stats["compiles"] == 2
        hits = step.stats["cache_hits"]
        float(step(ids, ids))           # first signature still serves
        assert step.stats["compiles"] == 2
        assert step.stats["cache_hits"] == hits + 1

    def test_checkpoint_restore_continue_both_flag_settings(
            self, fsdp_llama, tmp_path):
        """Train 2 steps, checkpoint through the shared optimizer
        state plane, rebuild, restore, continue — the loss stream
        matches the straight-through run under BOTH kill-switch
        settings (DistTrainStep is an explicit whole-step API like
        TrainStep: the kill switch does not change its path, and the
        stream must prove it)."""
        build, ids = fsdp_llama
        import paddle_tpu.distributed as dist
        # ONE stream is both the checkpoint source and the reference
        # (DistTrainStep is an explicit whole-step API — the kill
        # switch does not change its path, so the streams must agree
        # across flag settings too): train 2 steps, save, keep
        # training — the post-save tail is what each restore leg must
        # reproduce
        m1, step1 = build(seed=7)
        [float(step1(ids, ids)) for _ in range(2)]
        dist.save_state_dict(
            {"model": m1.state_dict(), "opt": step1.state_dict()},
            str(tmp_path / "ck"))
        ref = [float(step1(ids, ids)) for _ in range(2)]
        for flag in (1, 0):
            paddle.set_flags({"FLAGS_sot_capture": flag})
            try:
                m2, step2 = build(seed=7)
                opt_sd = step2.state_dict()
                dist.load_state_dict(
                    {"model": m2.state_dict(), "opt": opt_sd},
                    str(tmp_path / "ck"))
                step2.set_state_dict(opt_sd)
                l2 = [float(step2(ids, ids)) for _ in range(2)]
                np.testing.assert_allclose(l2, ref, rtol=2e-4,
                                           err_msg=f"flag={flag}")
            finally:
                paddle.set_flags({"FLAGS_sot_capture": 1})


class TestBucketAssignment:
    def test_every_grad_in_exactly_one_bucket_order_preserved(self):
        from paddle_tpu.distributed.collective import bucket_assignment
        sizes = [(f"g{i}", 100) for i in range(10)]
        buckets = bucket_assignment(sizes, 250)
        flat = [k for b in buckets for k in b]
        assert flat == [k for k, _ in sizes]          # order preserved
        assert len(flat) == len(set(flat)) == 10      # exactly once
        # byte target respected: no bucket exceeds it unless a single
        # grad alone does
        for b in buckets:
            total = sum(100 for _ in b)
            assert total <= 250 or len(b) == 1

    def test_oversized_grad_gets_its_own_bucket(self):
        from paddle_tpu.distributed.collective import bucket_assignment
        sizes = [("a", 10), ("big", 1000), ("b", 10), ("c", 10)]
        buckets = bucket_assignment(sizes, 100)
        assert ["big"] in buckets
        flat = [k for b in buckets for k in b]
        assert flat == ["a", "big", "b", "c"]

    def test_disabled_target_single_bucket(self):
        from paddle_tpu.distributed.collective import bucket_assignment
        sizes = [("a", 10), ("b", 10)]
        assert bucket_assignment(sizes, 0) == [["a", "b"]]
        assert bucket_assignment([], 0) == []
        assert bucket_assignment([], 100) == []

class TestBucketedOverlapProgram:
    class _flag:
        """Hold FLAGS_dist_grad_bucket_bytes for a block: the target
        is a signature guard, so measurement must run under the same
        value the program was traced with."""

        def __init__(self, value):
            self.value = value

        def __enter__(self):
            self.prev = paddle.get_flags("FLAGS_dist_grad_bucket_bytes")
            paddle.set_flags(
                {"FLAGS_dist_grad_bucket_bytes": self.value})

        def __exit__(self, *exc):
            paddle.set_flags(self.prev)
            return False

    def test_program_structure_pinned(self, fsdp_llama):
        """The captured distributed program carries >= 2 gradient
        buckets as first-class nodes: vs the flag=0 epilogue program
        the jaxpr grows exactly (n_buckets - 1) optimization_barriers
        (the issue-order chain) and one sharding_constraint per
        bucketed grad; the compiled HLO carries >= 2 collective
        sites; and the FIRST bucket's sync transitively depends on
        only a fraction of the backward's dot_generals while the
        LAST depends on (almost) all — the DAG independence that
        lets async collectives overlap remaining backward compute."""
        import re
        import jax.core as jcore
        build, ids = fsdp_llama

        prev = paddle.get_flags("FLAGS_dist_grad_bucket_bytes")
        try:
            paddle.set_flags({"FLAGS_dist_grad_bucket_bytes": 2048})
            _, step_on = build()
            l_on = [float(step_on(ids, ids)) for _ in range(2)]
            plan = step_on.bucket_plan()
            assert len(plan) >= 2, plan
            jx_on = step_on.trace_jaxpr(ids, ids).jaxpr
            paddle.set_flags({"FLAGS_dist_grad_bucket_bytes": 0})
            _, step_off = build()
            l_off = [float(step_off(ids, ids)) for _ in range(2)]
            assert step_off.bucket_plan() == []
            jx_off = step_off.trace_jaxpr(ids, ids).jaxpr
        finally:
            paddle.set_flags(prev)
        # bucketing is semantically inert: the sync nodes materialize
        # the SAME reduced grads the epilogue program computes
        np.testing.assert_allclose(l_on, l_off, rtol=1e-6)

        def count(jaxpr, name):
            return sum(1 for e in jaxpr.eqns
                       if e.primitive.name == name)

        n_grads = sum(b["grads"] for b in plan)
        assert count(jx_on, "optimization_barrier") - \
            count(jx_off, "optimization_barrier") == len(plan) - 1
        assert count(jx_on, "sharding_constraint") - \
            count(jx_off, "sharding_constraint") == n_grads

        # HLO: the partitioner landed real collectives per bucket
        prev2 = paddle.get_flags("FLAGS_dist_grad_bucket_bytes")
        paddle.set_flags({"FLAGS_dist_grad_bucket_bytes": 2048})
        try:
            _, compiled, _ = step_on.compile_stats(
                ids, ids, return_compiled=True)
        finally:
            paddle.set_flags(prev2)
        n_coll = len(re.findall(r"(all-reduce|reduce-scatter)\(",
                                compiled.as_text()))
        assert n_coll >= 2, n_coll

        # dependency pin: walk the jaxpr DAG from each bucket sync
        eqns = jx_on.eqns
        prod = {}
        for i, e in enumerate(eqns):
            for ov in e.outvars:
                prod[id(ov)] = i
        dots = {i for i, e in enumerate(eqns)
                if e.primitive.name == "dot_general"}

        def dot_deps(i):
            seen, stack = set(), [i]
            while stack:
                j = stack.pop()
                if j in seen:
                    continue
                seen.add(j)
                for iv in eqns[j].invars:
                    if isinstance(iv, jcore.Literal):
                        continue
                    p = prod.get(id(iv))
                    if p is not None:
                        stack.append(p)
            return len(seen & dots)

        wsc = [i for i, e in enumerate(eqns)
               if e.primitive.name == "sharding_constraint"]
        # bucket syncs trace AFTER the forward's constraints: the last
        # n_grads sharding_constraint eqns are the bucket nodes, in
        # bucket issue order
        bucket_wsc = wsc[-n_grads:]
        first_deps = dot_deps(bucket_wsc[0])
        last_deps = dot_deps(bucket_wsc[-1])
        assert first_deps < last_deps, (first_deps, last_deps)
        # the first bucket must NOT need the whole backward — that
        # independence is the overlap window
        assert first_deps <= 0.7 * len(dots), (first_deps, len(dots))

    def test_per_bucket_flight_events_each_step(self, fsdp_llama):
        build, ids = fsdp_llama
        with self._flag(2048):
            m, step = build()
            float(step(ids, ids))
            plan = step.bucket_plan()
            assert len(plan) >= 2
            # the plan walks grads in REVERSE registration (forward)
            # order — the last layers' grads, which backward retires
            # first, land in the first buckets — each exactly once
            flat = [k for b in plan for k in b["keys"]]
            reg_order = [k for k, p in m.named_parameters()
                         if not p.stop_gradient]
            assert flat == list(reversed(reg_order))
            flight.clear()
            float(step(ids, ids))
        ev = [e for e in flight.events(category="collective")
              if e["name"] == "grad_bucket"]
        assert len(ev) == len(plan), (len(ev), len(plan))
        assert [e["attrs"]["bytes"] for e in ev] == \
            [b["bytes"] for b in plan]
        summary = [e for e in flight.events(category="collective")
                   if e["name"] == "dist_step"]
        assert summary and \
            summary[-1]["attrs"]["buckets"] == len(plan)
        assert summary[-1]["attrs"]["dur_us"] > 0
        # flag round-trip onto CACHED programs: plans are keyed per
        # (bucket_bytes, trainable set), so an epilogue replay reports
        # no buckets and journals nothing, and flipping back restores
        # THIS program's plan — no retrace, no phantom telemetry
        with self._flag(0):
            float(step(ids, ids))            # traces the epilogue once
            flight.clear()
            float(step(ids, ids))            # cached epilogue replay
            assert step.bucket_plan() == []
            assert not [e for e in flight.events(category="collective")
                        if e["name"] == "grad_bucket"]
        with self._flag(2048):
            flight.clear()
            float(step(ids, ids))            # cached bucketed replay
            assert step.bucket_plan() == plan
            assert len([e for e in flight.events(category="collective")
                        if e["name"] == "grad_bucket"]) == len(plan)
        assert step.stats["compiles"] == 2   # one per flag value
