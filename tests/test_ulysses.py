"""Ulysses all-to-all sequence-parallel attention tests (above-parity
feature; parity gate is against full attention, like ring attention)."""
import numpy as np
import pytest

import paddle_tpu as paddle  # noqa: F401  (backend setup via conftest)


@pytest.fixture
def qkv(rng):
    import jax.numpy as jnp
    B, L, H, D = 2, 32, 8, 16
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    return mk(), mk(), mk()


def _mesh():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "sp"))


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, qkv, causal):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from paddle_tpu.distributed.ulysses import ulysses_attention
        from paddle_tpu.ops.pallas.flash_attention import _sdpa_xla

        q, k, v = qkv
        mesh = _mesh()
        sh = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        ref = _sdpa_xla(q, k, v, causal=causal)
        out = ulysses_attention(qs, ks, vs, mesh, "sp", causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_matches_ring_attention(self, qkv):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from paddle_tpu.distributed.ring_attention import ring_attention
        from paddle_tpu.distributed.ulysses import ulysses_attention

        q, k, v = qkv
        mesh = _mesh()
        sh = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        ring = ring_attention(qs, ks, vs, mesh, "sp", causal=True)
        uly = ulysses_attention(qs, ks, vs, mesh, "sp", causal=True)
        np.testing.assert_allclose(np.asarray(uly), np.asarray(ring),
                                   atol=2e-5)

    def test_head_divisibility_check(self, qkv):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from paddle_tpu.distributed.ulysses import ulysses_attention

        q, k, v = qkv
        q6 = q[:, :, :6]  # 6 heads not divisible by sp=4
        mesh = _mesh()
        with pytest.raises(ValueError, match="must divide"):
            ulysses_attention(q6, k[:, :, :6], v[:, :, :6], mesh, "sp")

    def test_grad_flows(self, qkv):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from paddle_tpu.distributed.ulysses import ulysses_attention

        q, k, v = qkv
        mesh = _mesh()
        sh = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

        def loss(q_, k_, v_):
            return ulysses_attention(q_, k_, v_, mesh, "sp").sum()

        g = jax.grad(loss)(qs, ks, vs)
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).max()) > 0
