"""Tensor basics: creation, dtype, methods, indexing, interop.

Modeled on the reference's OpTest style of NumPy-reference comparison
(ref: test/legacy_test/op_test.py check_output)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestCreation:
    def test_to_tensor(self):
        t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert t.shape == [2, 2]
        assert t.dtype == np.float32
        np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])

    def test_to_tensor_dtype(self):
        t = paddle.to_tensor([1, 2, 3], dtype="float32")
        assert t.dtype == np.float32

    def test_zeros_ones_full(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3]).numpy().sum() == 6
        np.testing.assert_allclose(paddle.full([2], 7.0).numpy(), [7, 7])

    def test_arange_linspace(self):
        np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(paddle.arange(1, 7, 2).numpy(), [1, 3, 5])
        np.testing.assert_allclose(
            paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5))

    def test_eye_like(self):
        np.testing.assert_allclose(paddle.eye(3).numpy(), np.eye(3))
        x = paddle.ones([2, 2])
        assert paddle.zeros_like(x).numpy().sum() == 0
        assert paddle.full_like(x, 3).numpy().sum() == 12

    def test_random_shapes(self):
        paddle.seed(42)
        a = paddle.randn([4, 5])
        assert a.shape == [4, 5]
        b = paddle.uniform([3], min=2.0, max=3.0)
        assert (b.numpy() >= 2).all() and (b.numpy() < 3).all()
        c = paddle.randint(0, 10, [20])
        assert ((c.numpy() >= 0) & (c.numpy() < 10)).all()

    def test_seed_determinism(self):
        paddle.seed(7)
        a = paddle.randn([8])
        paddle.seed(7)
        b = paddle.randn([8])
        np.testing.assert_array_equal(a.numpy(), b.numpy())

    def test_tril_triu(self):
        x = paddle.ones([3, 3])
        assert paddle.tril(x).numpy().sum() == 6
        assert paddle.triu(x, 1).numpy().sum() == 3


class TestTensorMethods:
    def test_properties(self):
        t = paddle.ones([2, 3, 4])
        assert t.ndim == 3
        assert t.size == 24
        assert t.numel() == 24
        assert len(t) == 2

    def test_item(self):
        assert paddle.to_tensor(3.5).item() == pytest.approx(3.5)
        assert float(paddle.to_tensor([2.0]).sum()) == 2.0

    def test_astype(self):
        t = paddle.to_tensor([1.7, 2.3])
        assert t.astype("int32").dtype == np.int32

    def test_operators(self):
        a = paddle.to_tensor([1.0, 2.0])
        b = paddle.to_tensor([3.0, 4.0])
        np.testing.assert_allclose((a + b).numpy(), [4, 6])
        np.testing.assert_allclose((a - b).numpy(), [-2, -2])
        np.testing.assert_allclose((a * b).numpy(), [3, 8])
        np.testing.assert_allclose((b / a).numpy(), [3, 2])
        np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
        np.testing.assert_allclose((2.0 * a).numpy(), [2, 4])
        np.testing.assert_allclose((1.0 - a).numpy(), [0, -1])
        np.testing.assert_allclose((-a).numpy(), [-1, -2])

    def test_comparison(self):
        a = paddle.to_tensor([1.0, 5.0])
        b = paddle.to_tensor([2.0, 2.0])
        np.testing.assert_array_equal((a < b).numpy(), [True, False])
        np.testing.assert_array_equal((a >= b).numpy(), [False, True])

    def test_matmul_operator(self):
        a = paddle.ones([2, 3])
        b = paddle.ones([3, 4])
        assert (a @ b).shape == [2, 4]

    def test_indexing(self):
        t = paddle.to_tensor(np.arange(12.0).reshape(3, 4))
        assert t[0, 1].item() == 1.0
        np.testing.assert_allclose(t[1].numpy(), [4, 5, 6, 7])
        np.testing.assert_allclose(t[:, 2].numpy(), [2, 6, 10])
        np.testing.assert_allclose(t[0:2, 0:2].numpy(), [[0, 1], [4, 5]])

    def test_setitem(self):
        t = paddle.zeros([3, 3])
        t[1, 1] = 5.0
        assert t.numpy()[1, 1] == 5.0

    def test_method_patching(self):
        t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert t.sum().item() == 10
        assert t.mean().item() == 2.5
        assert t.reshape([4]).shape == [4]
        assert t.transpose([1, 0]).shape == [2, 2]
        assert t.exp().shape == [2, 2]

    def test_inplace(self):
        t = paddle.ones([2])
        t.add_(paddle.ones([2]))
        np.testing.assert_allclose(t.numpy(), [2, 2])
        t.set_value(np.array([5.0, 6.0]))
        np.testing.assert_allclose(t.numpy(), [5, 6])

    def test_detach_clone(self):
        t = paddle.to_tensor([1.0], stop_gradient=False)
        d = t.detach()
        assert d.stop_gradient
        c = t.clone()
        assert not c.stop_gradient


class TestMathOps:
    def test_unary_matches_numpy(self, rng):
        x = rng.uniform(0.1, 2.0, (3, 4)).astype(np.float32)
        t = paddle.to_tensor(x)
        for pfn, nfn in [
            (paddle.sqrt, np.sqrt), (paddle.exp, np.exp), (paddle.log, np.log),
            (paddle.sin, np.sin), (paddle.cos, np.cos), (paddle.tanh, np.tanh),
            (paddle.floor, np.floor), (paddle.abs, np.abs),
            (paddle.square, np.square),
        ]:
            # XLA CPU fast-math transcendentals differ from libm at ~1e-4 rel
            np.testing.assert_allclose(pfn(t).numpy(), nfn(x), rtol=1e-3,
                                       atol=1e-5)

    def test_reductions(self, rng):
        x = rng.standard_normal((2, 3, 4)).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(t.sum().item(), x.sum(), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.sum(t, axis=1).numpy(), x.sum(1), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.mean(t, axis=[0, 2]).numpy(), x.mean((0, 2)), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.max(t, axis=2, keepdim=True).numpy(),
            x.max(2, keepdims=True))
        np.testing.assert_allclose(paddle.std(t).item(), x.std(ddof=1),
                                   rtol=1e-4)

    def test_argmax_topk_sort(self, rng):
        x = rng.standard_normal((5, 6)).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_array_equal(
            paddle.argmax(t, axis=1).numpy(), x.argmax(1))
        vals, idx = paddle.topk(t, 3, axis=1)
        np.testing.assert_allclose(vals.numpy(), -np.sort(-x, 1)[:, :3],
                                   rtol=1e-6)
        np.testing.assert_allclose(
            paddle.sort(t, axis=0).numpy(), np.sort(x, 0))

    def test_cumsum_clip(self, rng):
        x = rng.standard_normal(10).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.cumsum(t).numpy(), np.cumsum(x),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            paddle.clip(t, -0.5, 0.5).numpy(), np.clip(x, -0.5, 0.5))

    def test_where_nonzero(self):
        x = paddle.to_tensor([1.0, -1.0, 2.0])
        y = paddle.zeros([3])
        np.testing.assert_allclose(
            paddle.where(x > 0, x, y).numpy(), [1, 0, 2])
        nz = paddle.nonzero(paddle.to_tensor([0, 3, 0, 5]))
        np.testing.assert_array_equal(nz.numpy(), [[1], [3]])

    def test_logic(self):
        a = paddle.to_tensor([True, False])
        b = paddle.to_tensor([True, True])
        np.testing.assert_array_equal(
            paddle.logical_and(a, b).numpy(), [True, False])
        assert paddle.all(b).item()
        assert not paddle.all(a).item()


class TestManipulation:
    def test_reshape_transpose(self, rng):
        x = rng.standard_normal((2, 3, 4)).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(
            paddle.reshape(t, [6, 4]).numpy(), x.reshape(6, 4))
        np.testing.assert_allclose(
            paddle.reshape(t, [-1, 2]).numpy(), x.reshape(-1, 2))
        np.testing.assert_allclose(
            paddle.transpose(t, [2, 0, 1]).numpy(), x.transpose(2, 0, 1))

    def test_concat_stack_split(self, rng):
        x = rng.standard_normal((2, 3)).astype(np.float32)
        y = rng.standard_normal((2, 3)).astype(np.float32)
        tx, ty = paddle.to_tensor(x), paddle.to_tensor(y)
        np.testing.assert_allclose(
            paddle.concat([tx, ty], axis=0).numpy(),
            np.concatenate([x, y], 0))
        np.testing.assert_allclose(
            paddle.stack([tx, ty], axis=1).numpy(), np.stack([x, y], 1))
        parts = paddle.split(paddle.to_tensor(np.arange(10.0)), 5)
        assert len(parts) == 5 and parts[0].shape == [2]
        parts = paddle.split(paddle.to_tensor(np.arange(10.0)), [3, 7])
        assert parts[0].shape == [3] and parts[1].shape == [7]
        parts = paddle.split(paddle.to_tensor(np.arange(10.0)), [3, -1])
        assert parts[1].shape == [7]

    def test_squeeze_unsqueeze_flatten(self):
        t = paddle.ones([1, 3, 1, 4])
        assert paddle.squeeze(t).shape == [3, 4]
        assert paddle.squeeze(t, axis=0).shape == [3, 1, 4]
        assert paddle.unsqueeze(paddle.ones([3]), 0).shape == [1, 3]
        assert paddle.flatten(paddle.ones([2, 3, 4]),
                              start_axis=1).shape == [2, 12]

    def test_expand_tile(self):
        t = paddle.ones([1, 3])
        assert paddle.expand(t, [4, 3]).shape == [4, 3]
        assert paddle.expand(t, [4, -1]).shape == [4, 3]
        assert paddle.tile(t, [2, 2]).shape == [2, 6]

    def test_gather_scatter(self, rng):
        x = rng.standard_normal((5, 3)).astype(np.float32)
        t = paddle.to_tensor(x)
        idx = paddle.to_tensor([0, 2], dtype="int32")
        np.testing.assert_allclose(
            paddle.gather(t, idx, axis=0).numpy(), x[[0, 2]])
        upd = paddle.ones([2, 3])
        out = paddle.scatter(t, idx, upd)
        np.testing.assert_allclose(out.numpy()[0], np.ones(3))

    def test_take_along_put_along(self, rng):
        x = rng.standard_normal((3, 4)).astype(np.float32)
        idx = np.argsort(x, axis=1)
        out = paddle.take_along_axis(
            paddle.to_tensor(x), paddle.to_tensor(idx), axis=1)
        np.testing.assert_allclose(out.numpy(), np.take_along_axis(x, idx, 1))

    def test_pad(self):
        t = paddle.ones([1, 1, 2, 2])
        out = paddle.pad(t, [1, 1, 1, 1])
        assert out.shape == [1, 1, 4, 4]
        assert out.numpy().sum() == 4

    def test_flip_roll(self):
        t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        np.testing.assert_allclose(
            paddle.flip(t, 0).numpy(), [[3, 4], [1, 2]])
        np.testing.assert_allclose(
            paddle.roll(t, 1, axis=1).numpy(), [[2, 1], [4, 3]])


class TestLinalg:
    def test_matmul(self, rng):
        a = rng.standard_normal((3, 4)).astype(np.float32)
        b = rng.standard_normal((4, 5)).astype(np.float32)
        np.testing.assert_allclose(
            paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
            a @ b, rtol=1e-5)
        np.testing.assert_allclose(
            paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b.T),
                          transpose_y=True).numpy(), a @ b, rtol=1e-5)

    def test_einsum(self, rng):
        a = rng.standard_normal((2, 3)).astype(np.float32)
        b = rng.standard_normal((3, 4)).astype(np.float32)
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a),
                            paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)

    def test_norm_inverse_solve(self, rng):
        a = rng.standard_normal((3, 3)).astype(np.float32)
        a = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        ta = paddle.to_tensor(a)
        np.testing.assert_allclose(paddle.norm(ta).item(),
                                   np.linalg.norm(a), rtol=1e-5)
        np.testing.assert_allclose(paddle.inverse(ta).numpy(),
                                   np.linalg.inv(a), rtol=1e-3, atol=1e-5)
        b = rng.standard_normal((3,)).astype(np.float32)
        np.testing.assert_allclose(
            paddle.solve(ta, paddle.to_tensor(b)).numpy(),
            np.linalg.solve(a, b), rtol=1e-3, atol=1e-5)

    def test_svd_qr_eigh(self, rng):
        a = rng.standard_normal((4, 3)).astype(np.float32)
        u, s, vh = paddle.svd(paddle.to_tensor(a))
        np.testing.assert_allclose(
            (u.numpy() * s.numpy()) @ vh.numpy(), a, rtol=1e-3, atol=1e-4)
        q, r = paddle.qr(paddle.to_tensor(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, rtol=1e-3,
                                   atol=1e-4)

    def test_saved_load_roundtrip(self, tmp_path):
        state = {"w": paddle.randn([3, 3]).astype("bfloat16"),
                 "step": 7, "nested": {"b": paddle.ones([2])}}
        p = str(tmp_path / "ckpt.pdparams")
        paddle.save(state, p)
        loaded = paddle.load(p)
        assert loaded["step"] == 7
        np.testing.assert_array_equal(
            loaded["w"].astype("float32").numpy(),
            state["w"].astype("float32").numpy())
