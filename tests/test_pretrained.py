"""Pretrained-weight machinery (ref: python/paddle/utils/download.py +
vision/models/resnet.py pretrained branch): cache/md5, the
PADDLE_TPU_PRETRAINED_DIR local override, and a reference-format weight
round-trip through resnet18(pretrained=True)."""
import hashlib
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils.download import (_md5check, get_weights_path_from_url)
from paddle_tpu.vision.models import resnet18
from paddle_tpu.vision.models.resnet import load_pretrained, model_urls


def _make_reference_format_weights(tmp_path, fname="resnet18.pdparams"):
    """A weights file exactly as the reference publishes them: a pickle
    of {param_name: numpy array} (paddle.save converts tensors to
    ndarray before pickling)."""
    paddle.seed(123)
    src = resnet18(num_classes=1000)
    state = {k: np.asarray(v._data) for k, v in src.state_dict().items()}
    p = tmp_path / fname
    with open(p, "wb") as f:
        pickle.dump(state, f, protocol=4)
    md5 = hashlib.md5(p.read_bytes()).hexdigest()
    return src, str(p), md5


class TestDownloadMachinery:
    def test_md5check(self, tmp_path):
        p = tmp_path / "blob"
        p.write_bytes(b"hello")
        good = hashlib.md5(b"hello").hexdigest()
        assert _md5check(str(p), good)
        assert not _md5check(str(p), "0" * 32)
        assert _md5check(str(p), None)

    def test_local_override_resolves(self, tmp_path, monkeypatch):
        _, path, md5 = _make_reference_format_weights(tmp_path)
        monkeypatch.setenv("PADDLE_TPU_PRETRAINED_DIR", str(tmp_path))
        got = get_weights_path_from_url(
            "https://paddle-hapi.bj.bcebos.com/models/resnet18.pdparams",
            md5)
        assert got == path

    def test_local_override_md5_mismatch_raises(self, tmp_path,
                                                monkeypatch):
        _, path, _ = _make_reference_format_weights(tmp_path)
        monkeypatch.setenv("PADDLE_TPU_PRETRAINED_DIR", str(tmp_path))
        with pytest.raises(ValueError, match="md5"):
            get_weights_path_from_url(
                "https://x/resnet18.pdparams", "0" * 32)

    def test_offline_fails_loudly_with_instructions(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_PRETRAINED_DIR", raising=False)
        monkeypatch.setenv("PADDLE_TPU_WEIGHTS_HOME", str(tmp_path))
        import paddle_tpu.utils.download as dl
        monkeypatch.setattr(dl, "WEIGHTS_HOME", str(tmp_path))
        with pytest.raises(RuntimeError,
                           match="PADDLE_TPU_PRETRAINED_DIR"):
            dl.get_weights_path_from_url(
                "https://invalid.example.invalid/w.pdparams", None)

    def test_cache_hit_skips_download(self, tmp_path, monkeypatch):
        import paddle_tpu.utils.download as dl
        monkeypatch.setattr(dl, "WEIGHTS_HOME", str(tmp_path))
        monkeypatch.delenv("PADDLE_TPU_PRETRAINED_DIR", raising=False)
        cached = tmp_path / "w.pdparams"
        cached.write_bytes(b"cached-bytes")
        md5 = hashlib.md5(b"cached-bytes").hexdigest()
        # url host is unreachable — must resolve purely from cache
        got = dl.get_weights_path_from_url(
            "https://invalid.example.invalid/w.pdparams", md5)
        assert got == str(cached)


class TestPretrainedRoundTrip:
    def test_resnet18_pretrained_true_roundtrip(self, tmp_path,
                                                monkeypatch):
        """resnet18(pretrained=True) must install reference-format
        weights bit-exactly (the VERDICT round-trip gate)."""
        src, path, md5 = _make_reference_format_weights(tmp_path)
        monkeypatch.setenv("PADDLE_TPU_PRETRAINED_DIR", str(tmp_path))
        monkeypatch.setitem(
            model_urls, "resnet18",
            ("https://paddle-hapi.bj.bcebos.com/models/resnet18.pdparams",
             md5))
        paddle.seed(999)  # different init: loading must overwrite it
        m = resnet18(pretrained=True)
        for (k1, v1), (k2, v2) in zip(sorted(src.state_dict().items()),
                                      sorted(m.state_dict().items())):
            assert k1 == k2
            np.testing.assert_array_equal(np.asarray(v1._data),
                                          np.asarray(v2._data))

    def test_mismatched_weights_fail_loudly(self, tmp_path, monkeypatch):
        src, path, md5 = _make_reference_format_weights(tmp_path)
        monkeypatch.setenv("PADDLE_TPU_PRETRAINED_DIR", str(tmp_path))
        monkeypatch.setitem(
            model_urls, "resnet18",
            ("https://paddle-hapi.bj.bcebos.com/models/resnet18.pdparams",
             md5))
        m = resnet18(num_classes=7)  # fc shape mismatch
        with pytest.raises(Exception):
            load_pretrained(m, "resnet18")

    def test_unknown_arch_raises(self):
        m = resnet18()
        with pytest.raises(ValueError, match="no published pretrained"):
            load_pretrained(m, "resnet9000")


class TestArchKeyNormalization:
    """Regression: hand-built arch strings produced unmatchable keys
    (squeezenet '1.0' vs '1_0'; integer scale '1' vs '1.0')."""

    def test_scale_suffix(self):
        from paddle_tpu.vision.models._utils import scale_suffix
        assert scale_suffix(1) == "1.0"
        assert scale_suffix(1.0) == "1.0"
        assert scale_suffix(0.25) == "0.25"
        assert scale_suffix("0.5") == "0.5"

    @pytest.mark.slow  # ~53s: constructs every zoo CNN to reach the probe
    def test_zoo_arch_keys_exist(self, monkeypatch, tmp_path):
        """Every zoo constructor's pretrained branch must build an arch
        key that exists in its model_urls (probe by capturing the key at
        the loader boundary)."""
        import paddle_tpu.vision.models._utils as mu
        from paddle_tpu.vision import models as M

        seen = []

        def probe(model, arch, urls):
            assert arch in urls, f"{arch} not in {sorted(urls)}"
            seen.append(arch)
            raise _Probed()

        class _Probed(Exception):
            pass

        monkeypatch.setattr(mu, "load_pretrained", probe)
        cases = [
            lambda: M.squeezenet1_0(pretrained=True),
            lambda: M.squeezenet1_1(pretrained=True),
            lambda: M.mobilenet_v1(pretrained=True, scale=1),
            lambda: M.mobilenet_v2(pretrained=True, scale=1.0),
            lambda: M.mobilenet_v3_small(pretrained=True, scale=1),
            lambda: M.mobilenet_v3_large(pretrained=True, scale=1),
            lambda: M.shufflenet_v2_x1_0(pretrained=True),
            lambda: M.vgg16(pretrained=True),
            lambda: M.alexnet(pretrained=True),
            lambda: M.densenet121(pretrained=True),
            lambda: M.googlenet(pretrained=True),
            lambda: M.inception_v3(pretrained=True),
        ]
        for c in cases:
            with pytest.raises(_Probed):
                c()
        assert len(seen) == len(cases)
