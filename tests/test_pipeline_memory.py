"""Pipeline memory gate (VERDICT round-1 item 7).

Live-measures the compiled pipeline's memory via XLA's
compile-time memory analysis (the CPU-mesh analog of
jax.device_memory_profile): remat must cut peak temps, and with
remat="full" the per-extra-micro-batch growth must be a single carried
activation, not the stage-internal residual footprint.
ref: fleet/meta_parallel/pipeline_parallel.py:575-720 (what 1F1B buys)
+ the recompute pass (auto_parallel_recompute).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel.pipeline_spmd import (remat_policy, spmd_pipeline,
                                               stack_layer_params)

S = 4          # pipeline stages
B, H = 8, 64   # micro-batch rows, hidden
DEPTH = 6      # matmuls per stage -> fat stage-internal residuals


def _mesh():
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()[:S]), ("pp",))


def _stage_fn(p, x):
    y = x
    for i in range(DEPTH):
        y = jnp.tanh(y @ p[f"w{i}"])
    return y


def _params():
    rng = np.random.default_rng(0)
    per_layer = [{f"w{i}": jnp.asarray(
        rng.standard_normal((H, H)).astype(np.float32) * 0.1)
        for i in range(DEPTH)} for _ in range(S)]
    return stack_layer_params(per_layer)


def _peak_temp_bytes(m_micro, remat):
    mesh = _mesh()
    params = _params()

    def loss(p, mb):
        out = spmd_pipeline(_stage_fn, p, mb, mesh, remat=remat)
        return jnp.sum(out ** 2)

    grad_fn = jax.jit(jax.grad(loss))
    mb = jnp.zeros((m_micro, B, H), jnp.float32)
    c = grad_fn.lower(params, mb).compile()
    return c.memory_analysis().temp_size_in_bytes


class TestPipelineRemat:
    def test_remat_policies_resolve(self):
        assert remat_policy("none") is None
        assert remat_policy("full") is not None
        assert remat_policy("dots") is not None
        with pytest.raises(ValueError):
            remat_policy("bogus")

    def test_numerics_unchanged_by_remat(self):
        mesh = _mesh()
        params = _params()
        mb = jnp.asarray(np.random.default_rng(1).standard_normal(
            (8, B, H)).astype(np.float32))

        def loss(p, mb, remat):
            return jnp.sum(spmd_pipeline(_stage_fn, p, mb, mesh,
                                         remat=remat) ** 2)

        base = jax.grad(functools.partial(loss, remat=None))(params, mb)
        for mode in ("dots", "full"):
            got = jax.grad(functools.partial(loss, remat=mode))(params, mb)
            for k in base:
                np.testing.assert_allclose(np.asarray(got[k]),
                                           np.asarray(base[k]),
                                           rtol=2e-5, atol=1e-6)

    def test_remat_cuts_peak_memory(self):
        m = 8
        peak_none = _peak_temp_bytes(m, None)
        peak_full = _peak_temp_bytes(m, "full")
        assert peak_full < peak_none, (peak_full, peak_none)

    def test_full_remat_growth_is_one_activation_per_tick(self):
        """Doubling M must grow the remat='full' footprint by ~one carried
        activation per extra tick — NOT by the stage-internal residual
        set (DEPTH activations per tick without remat)."""
        act_bytes = B * H * 4
        m1, m2 = 8, 16
        g_full = _peak_temp_bytes(m2, "full") - _peak_temp_bytes(m1, "full")
        g_none = _peak_temp_bytes(m2, None) - _peak_temp_bytes(m1, None)
        ticks = m2 - m1
        # without remat each extra tick stores the DEPTH tanh outputs too
        assert g_none >= ticks * act_bytes * (DEPTH * 0.8)
        # with full remat: the carried activation + the [M,B,H] outs
        # buffer slot + small bookkeeping (measured 3.01x act/tick)
        assert g_full <= ticks * act_bytes * 3.5, (g_full, g_none)
        assert g_full < g_none / 2
