"""Semi-auto parallel API completion (VERDICT round-1 item 6).

ref contract: python/paddle/distributed/auto_parallel/api.py
shard_optimizer/:1613, shard_scaler/:2132, shard_dataloader/:2715,
to_static/DistModel/Strategy.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed import ProcessMesh


def _mesh1d(n=8, name="x"):
    return ProcessMesh(np.arange(n), dim_names=[name])


@pytest.mark.skipif(not os.path.isdir("/root/reference"),
                    reason="reference checkout absent in this container")
class TestDistAllSurface:
    def test_distributed_all_covered(self):
        import ast
        src = open(
            "/root/reference/python/paddle/distributed/__init__.py").read()
        tree = ast.parse(src)
        ref = None
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        ref = [ast.literal_eval(e) for e in node.value.elts]
        missing = [n for n in ref if not hasattr(dist, n)]
        assert missing == [], missing


class TestShardOptimizer:
    def _model_and_data(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8))
        x = np.random.default_rng(0).standard_normal((16, 8)).astype(
            np.float32)
        return net, x

    def test_default_inherits_param_placements(self):
        mesh = _mesh1d()
        net, x = self._model_and_data()
        for p in net.parameters():
            dist.shard_tensor(p, mesh, [dist.Replicate()])
        opt = dist.shard_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=net.parameters()))
        out = net(paddle.to_tensor(x))
        (out * out).mean().backward()
        opt.step()
        opt.clear_grad()
        # state slots exist and step ran
        assert opt._inner._states

    def test_sharding_stage1_places_moments(self):
        mesh = _mesh1d()
        net, x = self._model_and_data()
        from paddle_tpu.distributed.api import shard_parameter
        for p in net.parameters():
            # replicated params on the mesh (pure dp)
            shard_parameter(p, mesh)
        opt = dist.shard_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=net.parameters()),
            dist.ShardingStage1(mesh))
        out = net(paddle.to_tensor(x))
        (out * out).mean().backward()
        opt.step()
        # moment slots must be sharded on the mesh axis (ZeRO-1)
        some = next(iter(opt._inner._states.values()))
        m = some.get("m", some.get("moment1"))
        assert m is not None
        spec = m.sharding.spec if hasattr(m, "sharding") else None
        assert spec is not None and any(s is not None for s in spec), spec

    def test_sharded_training_matches_unsharded(self):
        mesh = _mesh1d()

        def run(shard):
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 8))
            opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=net.parameters())
            if shard:
                from paddle_tpu.distributed.api import shard_parameter
                for p in net.parameters():
                    shard_parameter(p, mesh)
                opt = dist.shard_optimizer(opt, dist.ShardingStage3(mesh))
            x = paddle.to_tensor(np.random.default_rng(1).standard_normal(
                (16, 8)).astype(np.float32))
            losses = []
            for _ in range(4):
                out = net(x)
                loss = (out * out).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            return losses

        np.testing.assert_allclose(run(False), run(True), rtol=2e-5)

    def test_mesh_change_checkpoint_roundtrip(self, tmp_path):
        """Opt state saved under one mesh restores under another
        (VERDICT: mesh-change checkpoint test) — reshard-on-load."""
        from paddle_tpu.distributed.api import shard_parameter
        from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                       save_state_dict)

        def build(mesh):
            paddle.seed(0)
            net = nn.Linear(8, 8)
            opt = dist.shard_optimizer(
                paddle.optimizer.AdamW(learning_rate=1e-2,
                                       parameters=net.parameters()),
                dist.ShardingStage1(mesh))
            x = paddle.to_tensor(np.ones((4, 8), np.float32))
            out = net(x)
            (out * out).mean().backward()
            opt.step()
            return net, opt

        mesh_a = _mesh1d(8, "x")
        net_a, opt_a = build(mesh_a)
        state = {}
        for i, (pid, slots) in enumerate(opt_a._inner._states.items()):
            for name, v in slots.items():
                if hasattr(v, "shape") and np.ndim(v) > 0:
                    state[f"p{i}#{name}"] = paddle.to_tensor(np.asarray(v))
        save_state_dict(state, str(tmp_path / "ckpt"))

        mesh_b = ProcessMesh(np.arange(8).reshape(2, 4),
                             dim_names=["a", "b"])
        net_b, opt_b = build(mesh_b)
        target = {}
        for i, (pid, slots) in enumerate(opt_b._inner._states.items()):
            for name, v in slots.items():
                if hasattr(v, "shape") and np.ndim(v) > 0:
                    target[f"p{i}#{name}"] = paddle.to_tensor(np.asarray(v))
        load_state_dict(target, str(tmp_path / "ckpt"))
        for k in state:
            np.testing.assert_allclose(np.asarray(target[k]._data),
                                       np.asarray(state[k]._data),
                                       rtol=1e-6)


class TestShardScalerAndDataloader:
    def test_shard_scaler_local_noop(self):
        net = nn.Linear(4, 4)
        scaler = dist.shard_scaler(paddle.amp.GradScaler())
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = scaler.scale((net(x) ** 2).mean())
        loss.backward()
        scaler.step(opt)
        scaler.update()

    def test_shard_dataloader(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        mesh = _mesh1d(8, "dp")
        xs = np.arange(64, dtype=np.float32).reshape(16, 4)
        ys = np.arange(16, dtype=np.int64)
        ds = TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])
        loader = DataLoader(ds, batch_size=8, shuffle=False)
        sl = dist.shard_dataloader(loader, mesh, shard_dims="dp")
        assert len(sl) == len(loader)
        batches = list(sl)
        assert len(batches) == 2
        xb, yb = batches[0]
        assert xb._dist_attr is not None
        assert isinstance(xb._dist_attr.placements[0], dist.Shard)
        np.testing.assert_allclose(np.asarray(xb._data), xs[:8])


class TestDistModelToStatic:
    def _setup(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
        loss = nn.MSELoss()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        return net, loss, opt

    def test_train_eval_predict_modes(self):
        net, loss, opt = self._setup()
        model = dist.to_static(net, loss=loss, optimizer=opt,
                               strategy=dist.Strategy())
        assert model.mode == "train"
        x = np.random.default_rng(0).standard_normal((8, 8)).astype(
            np.float32)
        y = np.zeros((8, 1), np.float32)
        losses = [float(model(x, y)) for _ in range(5)]
        assert losses[-1] < losses[0], losses

        model.eval()
        ev = float(model(paddle.to_tensor(x), paddle.to_tensor(y)))
        assert np.isfinite(ev)
        model.predict()
        out = model(paddle.to_tensor(x))
        assert list(out.shape) == [8, 1]

    def test_state_dict_roundtrip(self):
        net, loss, opt = self._setup()
        model = dist.to_static(net, loss=loss, optimizer=opt)
        x = np.ones((4, 8), np.float32)
        model(x, np.zeros((4, 1), np.float32))
        sd = model.state_dict()
        assert any("#" in k for k in sd)       # opt slots included
        assert any("#" not in k for k in sd)   # params included
        model.set_state_dict(sd)

    def test_strategy_fields(self):
        s = dist.Strategy({"sharding": {"enable": True, "stage": 2},
                           "pipeline": {"enable": True,
                                        "accumulate_steps": 4}})
        assert s.sharding.enable and s.sharding.stage == 2
        assert s.pipeline.accumulate_steps == 4
        assert s.amp.enable is False


class TestMisc:
    def test_gather_local(self):
        out = []
        dist.gather(paddle.to_tensor(np.ones(3, np.float32)), out, dst=0)
        assert len(out) == 1
        np.testing.assert_allclose(out[0].numpy(), 1.0)

    def test_wait_and_enums(self):
        t = paddle.to_tensor(np.ones(2, np.float32))
        dist.wait(t)
        assert dist.ParallelMode.DATA_PARALLEL == 0
        assert dist.ReduceType.kRedSum == 0

    def test_entries_and_datasets(self, tmp_path):
        assert dist.ProbabilityEntry(0.5)._to_attr() == \
            "probability_entry:0.5"
        assert dist.CountFilterEntry(3)._to_attr() == "count_filter_entry:3"
        assert dist.ShowClickEntry("show", "click")._to_attr() == \
            "show_click_entry:show:click"
        f = tmp_path / "slots.txt"
        f.write_text("a:1 a:2 b:3\na:4 b:5\n")
        ds = dist.InMemoryDataset()
        ds.init(batch_size=2, use_var=["a", "b"])
        ds.set_filelist([str(f)])
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 2
        batches = list(ds)
        assert set(batches[0].keys()) == {"a", "b"}

    def test_io_persistables(self, tmp_path):
        net = nn.Linear(3, 3)
        dist.io.save_persistables(net, str(tmp_path / "persist"))
        w0 = net.weight.numpy().copy()
        net.weight.set_value(np.zeros((3, 3), np.float32))
        dist.io.load_persistables(net, str(tmp_path / "persist"))
        np.testing.assert_allclose(net.weight.numpy(), w0)


class TestSpawn:
    def test_spawn_two_procs(self, tmp_path):
        import sys
        import subprocess
        import textwrap
        # spawn pickles func: run in a subprocess script for a clean env
        script = tmp_path / "sp.py"
        script.write_text(textwrap.dedent("""
            import os
            import paddle_tpu.distributed as dist

            def worker(path):
                import paddle_tpu.distributed as dist
                r = int(os.environ["PADDLE_TRAINER_ID"])
                open(f"{path}/rank{r}", "w").write("ok")

            if __name__ == "__main__":
                import sys
                dist.spawn(worker, args=(sys.argv[1],), nprocs=2)
                print("SPAWN_DONE")
        """))
        import os
        env = dict(os.environ, PYTHONPATH="/root/repo",
                   JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, str(script), str(tmp_path)],
            capture_output=True, text=True, timeout=120, env=env)
        assert proc.returncode == 0, proc.stderr
        assert "SPAWN_DONE" in proc.stdout
        assert (tmp_path / "rank0").exists()
        assert (tmp_path / "rank1").exists()


class TestEngineStrategyPasses:
    """VERDICT round-1 weak item 8: Engine applies real strategy passes
    (amp / sharding / gradient merge; recompute = fleet.utils.recompute).
    ref: passes/auto_parallel_{amp,sharding,gradient_merge}.py."""

    def test_gradient_merge_matches_full_batch(self):
        from paddle_tpu.distributed.dist_train import DistTrainStep

        def run(acc):
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 8))
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=net.parameters())
            step = DistTrainStep(net, lambda o, l: ((o - l) ** 2).mean(),
                                 opt, accumulate_steps=acc)
            rng = np.random.default_rng(1)
            x = rng.standard_normal((16, 8)).astype(np.float32)
            y = rng.standard_normal((16, 8)).astype(np.float32)
            return [float(step(x, y)) for _ in range(3)]

        np.testing.assert_allclose(run(1), run(4), rtol=1e-5)

    def test_engine_applies_amp_sharding_merge(self):
        from paddle_tpu.distributed.auto_parallel.engine import (Engine,
                                                                 Strategy)
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        strat = Strategy()
        strat.amp = {"enable": True, "dtype": "bfloat16", "level": "O2"}
        strat.sharding = {"enable": True, "stage": 1}
        strat.gradient_merge = {"enable": True, "k_steps": 2}
        mesh = _mesh1d(8, "dp")
        eng = Engine(net, lambda o, l: ((o - l) ** 2).mean(), opt,
                     strategy=strat, mesh=mesh)
        rng = np.random.default_rng(0)
        data = [(rng.standard_normal((8, 8)).astype(np.float32),
                 np.zeros((8, 8), np.float32)) for _ in range(6)]
        eng.fit(data, epochs=2)
        assert eng.history["loss"][-1] < eng.history["loss"][0]
        assert str(eng.model[0].weight.dtype) == "bfloat16"
        assert eng._step.accumulate_steps == 2

    def test_engine_amp_o1_keeps_fp32_weights(self):
        """O1 autocasts per-op but must NOT cast weights (the reference's
        O1 amp pass keeps fp32 masters; only O2 casts)."""
        from paddle_tpu.distributed.auto_parallel.engine import (Engine,
                                                                 Strategy)
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        strat = Strategy()
        strat.amp = {"enable": True, "dtype": "bfloat16"}  # default O1
        eng = Engine(net, lambda o, l: ((o - l) ** 2).mean(), opt,
                     strategy=strat)
        rng = np.random.default_rng(0)
        data = [(rng.standard_normal((8, 8)).astype(np.float32),
                 np.zeros((8, 8), np.float32)) for _ in range(2)]
        eng.fit(data, epochs=1)
        assert str(eng.model[0].weight.dtype) == "float32"
        assert np.isfinite(eng.history["loss"]).all()

    def test_memory_aware_recompute_on_fsdp_mesh(self):
        """VERDICT r3 item 10: recompute segments are chosen against the
        compiled step's measured peak (ref: passes/
        auto_parallel_recompute.py memory model), not a repeat count —
        a tight budget on the fsdp mesh triggers the wrap and the
        measured peak drops; a loose budget leaves the model alone."""
        import jax

        from paddle_tpu.distributed import ProcessMesh
        from paddle_tpu.distributed.auto_parallel.engine import (Engine,
                                                                 Strategy)

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        mesh = ProcessMesh(np.arange(8), dim_names=["fsdp"])

        def build(target):
            paddle.seed(0)
            blocks = [nn.Sequential(nn.Linear(64, 256), nn.Tanh(),
                                    nn.Linear(256, 64))
                      for _ in range(6)]
            net = nn.Sequential(*blocks)
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=net.parameters())
            strat = Strategy()
            strat.recompute = {"enable": True,
                               "target_peak_bytes": target}
            from paddle_tpu.distributed.api import shard_parameter
            eng = Engine(net, lambda o, l: ((o - l) ** 2).mean(), opt,
                         strategy=strat, mesh=mesh,
                         shard_fn=lambda m, mesh_: [
                             shard_parameter(p, mesh_)
                             for p in m.parameters()])
            rng = np.random.default_rng(0)
            # activations must dominate the peak for recompute to have
            # anything to reclaim: 4096 rows x 256 wide x 6 blocks of
            # stored f32 activations >> the 0.8MB of params
            x = rng.standard_normal((4096, 64)).astype(np.float32)
            eng.fit([(x, x)] * 2, epochs=1)
            return eng

        # tight budget: must wrap and reduce the measured peak
        eng = build(target=1)
        rep = eng.recompute_report
        assert rep["mode"] == "applied", rep
        assert rep["segments"] >= 2
        assert rep["peak_bytes_after"] < rep["peak_bytes_before"], rep
        assert np.isfinite(eng.history["loss"]).all()

        # loose budget: measured peak fits, nothing wrapped
        eng2 = build(target=10 ** 12)
        assert eng2.recompute_report["mode"] == "skipped", \
            eng2.recompute_report
        assert not any(getattr(l, "_recompute_wrapped", False)
                       for _, l in eng2.model.named_sublayers())

    def test_recompute_util(self):
        from paddle_tpu.distributed.fleet.utils import recompute
        paddle.seed(0)
        block = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                              nn.Linear(16, 8))
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32),
                             stop_gradient=False)
        out_r = recompute(block, x)
        np.testing.assert_allclose(out_r.numpy(), block(x).numpy(),
                                   rtol=1e-6)
        (out_r ** 2).sum().backward()
        gw = block[0].weight.grad.numpy().copy()
        gx = x.grad.numpy().copy()
        block[0].weight.clear_grad()
        x.clear_grad()
        (block(x) ** 2).sum().backward()
        # atol floors the comparison at f32 rounding: the recompute and
        # direct paths run different (both valid) XLA schedules, so
        # near-zero grad entries differ by ~1e-6 absolute — a bare rtol
        # turns that into an order-dependent flake
        np.testing.assert_allclose(gw, block[0].weight.grad.numpy(),
                                   rtol=1e-5, atol=2e-6)
        np.testing.assert_allclose(gx, x.grad.numpy(), rtol=1e-5,
                                   atol=2e-6)
