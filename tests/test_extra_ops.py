"""Numeric tests for the long-tail op surface + inplace variants
(ref: python/paddle/__init__.py __all__ parity)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _t(x):
    return paddle.to_tensor(np.asarray(x))


class TestExtraMath:
    def test_addmm(self):
        i = np.ones((2, 2), np.float32)
        a = np.array([[1., 2.], [3., 4.]], np.float32)
        b = np.eye(2, dtype=np.float32)
        out = paddle.addmm(_t(i), _t(a), _t(b), beta=0.5, alpha=2.0)
        np.testing.assert_allclose(out.numpy(), 0.5 * i + 2.0 * a)

    def test_logit_logcumsumexp(self):
        x = np.array([0.2, 0.5, 0.8], np.float32)
        np.testing.assert_allclose(paddle.logit(_t(x)).numpy(),
                                   np.log(x / (1 - x)), rtol=1e-5)
        y = np.array([1.0, 2.0, 3.0], np.float32)
        want = np.log(np.cumsum(np.exp(y)))
        np.testing.assert_allclose(paddle.logcumsumexp(_t(y)).numpy(),
                                   want, rtol=1e-5)

    def test_special_functions(self):
        x = np.array([0.5, 1.5, 3.0], np.float32)
        from scipy import special as sp
        np.testing.assert_allclose(paddle.gammaln(_t(x)).numpy(),
                                   sp.gammaln(x), rtol=1e-4)
        np.testing.assert_allclose(paddle.i0(_t(x)).numpy(), sp.i0(x),
                                   rtol=1e-4)
        np.testing.assert_allclose(
            paddle.multigammaln(_t(x + 2), 2).numpy(),
            sp.multigammaln(x + 2, 2), rtol=1e-4)

    def test_number_theory_and_angles(self):
        a = np.array([12, 18], np.int32)
        b = np.array([8, 27], np.int32)
        np.testing.assert_array_equal(paddle.gcd(_t(a), _t(b)).numpy(),
                                      np.gcd(a, b))
        np.testing.assert_array_equal(paddle.lcm(_t(a), _t(b)).numpy(),
                                      np.lcm(a, b))
        d = np.array([0.0, 90.0, 180.0], np.float32)
        np.testing.assert_allclose(paddle.deg2rad(_t(d)).numpy(),
                                   np.deg2rad(d), rtol=1e-6)

    def test_nan_to_num_heaviside_sgn(self):
        x = np.array([np.nan, np.inf, -np.inf, 2.0], np.float32)
        out = paddle.nan_to_num(_t(x), nan=0.0, posinf=9.0, neginf=-9.0)
        np.testing.assert_allclose(out.numpy(), [0.0, 9.0, -9.0, 2.0])
        h = paddle.heaviside(_t(np.array([-1.0, 0.0, 2.0], np.float32)),
                             _t(np.array([0.5], np.float32)))
        np.testing.assert_allclose(h.numpy(), [0.0, 0.5, 1.0])
        np.testing.assert_allclose(
            paddle.sgn(_t(np.array([-3.0, 0.0, 5.0], np.float32))).numpy(),
            [-1.0, 0.0, 1.0])

    def test_quantile_and_histogram(self):
        x = np.arange(10, dtype=np.float32)
        np.testing.assert_allclose(
            paddle.quantile(_t(x), 0.5).numpy(), np.quantile(x, 0.5))
        h = paddle.histogram(_t(x), bins=5, min=0, max=10)
        np.testing.assert_array_equal(h.numpy(), [2, 2, 2, 2, 2])
        hh, edges = paddle.histogramdd(_t(x[:, None]), bins=2)
        assert hh.numpy().sum() == 10

    def test_cdist_pdist(self):
        a = np.array([[0.0, 0.0], [3.0, 4.0]], np.float32)
        b = np.array([[0.0, 0.0]], np.float32)
        np.testing.assert_allclose(paddle.cdist(_t(a), _t(b)).numpy(),
                                   [[0.0], [5.0]], atol=1e-4)
        np.testing.assert_allclose(paddle.pdist(_t(a)).numpy(), [5.0],
                                   atol=1e-4)

    def test_stacking_and_splits(self):
        a, b = np.ones((2, 3), np.float32), np.zeros((2, 3), np.float32)
        assert paddle.hstack([_t(a), _t(b)]).shape == [2, 6]
        assert paddle.vstack([_t(a), _t(b)]).shape == [4, 3]
        assert paddle.dstack([_t(a), _t(b)]).shape == [2, 3, 2]
        parts = paddle.tensor_split(_t(np.arange(9)), 3)
        assert [p_.shape for p_ in parts] == [[3], [3], [3]]
        outs = paddle.unstack(_t(a), axis=0)
        assert len(outs) == 2 and outs[0].shape == [3]

    def test_construction(self):
        bd = paddle.block_diag([_t(np.ones((2, 2), np.float32)),
                                _t(np.full((1, 1), 3.0, np.float32))])
        assert bd.shape == [3, 3] and bd.numpy()[2, 2] == 3.0
        v = paddle.vander(_t(np.array([1.0, 2.0, 3.0], np.float32)), 3)
        np.testing.assert_allclose(v.numpy()[:, -1], [1, 1, 1])
        de = paddle.diag_embed(_t(np.array([[1.0, 2.0]], np.float32)))
        assert de.shape == [1, 2, 2]
        np.testing.assert_allclose(de.numpy()[0],
                                   np.diag([1.0, 2.0]))
        ti = paddle.tril_indices(3, 3, 0)
        assert ti.shape == [2, 6]

    def test_scatter_family(self):
        x = np.zeros((3, 4), np.float32)
        out = paddle.slice_scatter(_t(x),
                                   _t(np.ones((3, 2), np.float32)),
                                   axes=[1], starts=[1], ends=[3],
                                   strides=[1])
        np.testing.assert_allclose(out.numpy()[:, 1:3], 1.0)
        out2 = paddle.select_scatter(_t(x),
                                     _t(np.full((4,), 7.0, np.float32)),
                                     axis=0, index=1)
        np.testing.assert_allclose(out2.numpy()[1], 7.0)
        out3 = paddle.index_fill(_t(x), _t(np.array([0, 2])), 0, 5.0)
        np.testing.assert_allclose(out3.numpy()[[0, 2]], 5.0)

    def test_isin_bucketize_take(self):
        x = np.array([1, 3, 5], np.int64)
        out = paddle.isin(_t(x), _t(np.array([3, 5], np.int64)))
        np.testing.assert_array_equal(out.numpy(), [False, True, True])
        edges = np.array([2.0, 4.0], np.float32)
        b = paddle.bucketize(_t(np.array([1.0, 3.0, 9.0], np.float32)),
                             _t(edges))
        np.testing.assert_array_equal(b.numpy(), [0, 1, 2])
        t = paddle.take(_t(np.arange(6).reshape(2, 3)),
                        _t(np.array([[0, 5]])))
        np.testing.assert_array_equal(t.numpy(), [[0, 5]])

    def test_complex_helpers(self):
        r = np.array([1.0, 0.0], np.float32)
        i = np.array([0.0, 1.0], np.float32)
        c = paddle.complex(_t(r), _t(i))
        assert paddle.is_complex(c)
        back = paddle.as_real(c)
        np.testing.assert_allclose(back.numpy(), np.stack([r, i], -1))
        pol = paddle.polar(_t(np.array([1.0], np.float32)),
                           _t(np.array([np.pi / 2], np.float32)))
        np.testing.assert_allclose(pol.numpy().imag, [1.0], atol=1e-6)

    def test_unique_consecutive(self):
        x = np.array([1, 1, 2, 2, 2, 3, 1], np.int64)
        out, inv, counts = paddle.unique_consecutive(
            _t(x), return_inverse=True, return_counts=True)
        np.testing.assert_array_equal(out.numpy(), [1, 2, 3, 1])
        np.testing.assert_array_equal(counts.numpy(), [2, 3, 1, 1])

    def test_grad_flows_through_extra_ops(self):
        x = paddle.to_tensor(np.array([0.3, 0.6], np.float32),
                             stop_gradient=False)
        out = paddle.logit(x).sum()
        out.backward()
        want = 1 / (np.array([0.3, 0.6]) * (1 - np.array([0.3, 0.6])))
        np.testing.assert_allclose(x.grad.numpy(), want, rtol=1e-4)


class TestInplaceVariants:
    def test_unary_inplace(self):
        t = _t(np.array([1.0, 4.0, 9.0], np.float32))
        r = t.sqrt_()
        assert r is t
        np.testing.assert_allclose(t.numpy(), [1.0, 2.0, 3.0])

    def test_binary_inplace_and_toplevel(self):
        t = _t(np.array([2.0, 3.0], np.float32))
        t.add_(_t(np.array([1.0, 1.0], np.float32)))
        np.testing.assert_allclose(t.numpy(), [3.0, 4.0])
        paddle.multiply_(t, _t(np.array([2.0, 2.0], np.float32)))
        np.testing.assert_allclose(t.numpy(), [6.0, 8.0])

    def test_random_inplace(self):
        paddle.seed(0)
        t = _t(np.zeros((128,), np.float32))
        t.normal_(mean=5.0, std=0.1)
        assert abs(float(t.numpy().mean()) - 5.0) < 0.1
        t2 = _t(np.zeros((64,), np.float32))
        t2.uniform_(0.0, 1.0)
        assert 0.0 <= t2.numpy().min() and t2.numpy().max() <= 1.0

    def test_misc_top_level(self):
        assert paddle.iinfo("int8").max == 127
        assert paddle.finfo("bfloat16").bits == 16
        p_ = paddle.create_parameter([3, 3])
        assert not p_.stop_gradient
        assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
        assert paddle.binomial(_t(np.array([10.0], np.float32)),
                               _t(np.array([0.5], np.float32))
                               ).numpy()[0] <= 10


def test_cummax_cummin_gradients():
    """cummax/cummin values are differentiable — grads scatter to the
    running-extreme positions under the later-index tie rule (these ops
    previously built Tensors directly and silently dropped the tape)."""
    x = paddle.to_tensor(np.array([3., 1., 4., 1., 5.], np.float32),
                         stop_gradient=False)
    vals, idx = paddle.cummax(x)
    np.testing.assert_allclose(vals.numpy(), [3, 3, 4, 4, 5])
    np.testing.assert_allclose(idx.numpy(), [0, 0, 2, 2, 4])
    (vals ** 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [12., 0., 16., 0., 10.])
    x.clear_grad()
    v2, _ = paddle.cummin(x)
    np.testing.assert_allclose(v2.numpy(), [3, 1, 1, 1, 1])
    v2.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1., 2., 0., 2., 0.])
