"""incubate.asp (2:4 sparsity) + incubate.nn (fused layers) tests
(ref: python/paddle/incubate/asp/, incubate/nn/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.incubate import asp
from paddle_tpu.incubate import nn as inn


class TestASP:
    def test_create_mask_2_4(self):
        x = np.array([[0.1, -0.9, 0.5, 0.05, 2.0, -1.5, 0.2, 0.1]],
                     np.float32)
        mask = asp.create_mask(x)
        assert mask.shape == x.shape
        # per group of 4, exactly 2 kept, the largest-|.| ones
        np.testing.assert_array_equal(mask[0, :4], [0, 1, 1, 0])
        np.testing.assert_array_equal(mask[0, 4:], [1, 1, 0, 0])

    def test_check_sparsity_and_density(self):
        x = np.array([[1.0, 0, 2.0, 0], [0, 3.0, 0, 4.0]], np.float32)
        assert asp.check_sparsity(x)
        assert asp.calculate_density(x) == pytest.approx(0.5)
        dense = np.ones((2, 4), np.float32)
        assert not asp.check_sparsity(dense)

    def test_prune_model_and_decorated_step_keeps_masks(self):
        paddle.seed(0)
        m = nn.Linear(8, 4)
        opt = asp.decorate(paddle.optimizer.SGD(
            learning_rate=0.1, parameters=m.parameters()))
        pruned = asp.prune_model(m)
        assert any("weight" in k for k in pruned)
        assert asp.check_sparsity(m.weight.numpy())
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(16, 8))
            .astype(np.float32))
        for _ in range(3):
            loss = (m(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        # pruned entries stay exactly zero through training
        assert asp.check_sparsity(m.weight.numpy())
        assert asp.calculate_density(m.weight.numpy()) == \
            pytest.approx(0.5)

    def test_mask_2d_greedy(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 8)).astype(np.float32)
        mask = asp.create_mask(x, "mask_2d_greedy")
        assert mask.shape == x.shape
        assert asp.check_mask_2d(mask)
        # every 4x4 block keeps exactly 2 per row and per column
        blocks = mask.reshape(2, 4, 2, 4).transpose(0, 2, 1, 3)
        np.testing.assert_array_equal(blocks.sum(axis=-1), 2)
        np.testing.assert_array_equal(blocks.sum(axis=-2), 2)

    def test_mask_2d_best_beats_or_ties_greedy(self):
        rng = np.random.default_rng(1)
        for _ in range(5):
            x = rng.normal(size=(4, 4)).astype(np.float64)
            g = asp.create_mask(x, "mask_2d_greedy")
            b = asp.create_mask(x, "mask_2d_best")
            assert asp.check_mask_2d(b)
            assert np.abs(x * b).sum() >= np.abs(x * g).sum() - 1e-9

    def test_mask_2d_best_reference_example(self):
        # the reference docstring's worked example (utils.py
        # get_mask_2d_best): best retains L1=61 vs greedy's 56
        mat = np.array([[2, 8, 9, 9], [9, 1, 3, 9],
                        [5, 6, 3, 9], [2, 4, 6, 9]], np.float64)
        g = asp.create_mask(mat, "mask_2d_greedy")
        b = asp.create_mask(mat, "mask_2d_best")
        # our greedy tie-break retains 57 (reference's own ordering: 56);
        # best is the exhaustive optimum at 61 either way
        assert (mat * g).sum() >= 56.0
        assert (mat * b).sum() == pytest.approx(61.0)

    def test_mask_2d_padding_nonmultiple(self):
        x = np.arange(1, 31, dtype=np.float64).reshape(5, 6)
        mask = asp.create_mask(x, "mask_2d_greedy")
        assert mask.shape == x.shape
        assert asp.check_sparsity(mask[:4, :4], func_name="check_2d")

    def test_prune_model_2d_algo(self):
        paddle.seed(0)
        m = nn.Linear(8, 8)
        asp.prune_model(m, mask_algo="mask_2d_best")
        assert asp.check_mask_2d(m.weight.numpy())
        assert asp.calculate_density(m.weight.numpy()) == \
            pytest.approx(0.5)

    def test_excluded_layers(self):
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 4))
        asp.set_excluded_layers(["0.weight"])
        try:
            asp.prune_model(m)
            assert asp.calculate_density(m[0].weight.numpy()) == 1.0
            assert asp.calculate_density(m[1].weight.numpy()) == \
                pytest.approx(0.5)
        finally:
            asp.reset_excluded_layers()


class TestFusedNN:
    def test_fused_linear_matches_linear(self):
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(3, 5)).astype(np.float32))
        w = paddle.to_tensor(rng.normal(size=(5, 4)).astype(np.float32))
        b = paddle.to_tensor(rng.normal(size=(4,)).astype(np.float32))
        out = inn.functional.fused_linear(x, w, b)
        want = x.numpy() @ w.numpy() + b.numpy()
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-5)

    def test_fused_dropout_add_eval_is_add(self):
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        y = paddle.to_tensor(np.full((2, 3), 2.0, np.float32))
        out = inn.functional.fused_dropout_add(x, y, p=0.5, training=False)
        np.testing.assert_allclose(out.numpy(), 3.0 * np.ones((2, 3)))

    def test_fused_rms_and_layer_norm(self):
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(2, 6)).astype(np.float32))
        w = paddle.to_tensor(np.ones(6, np.float32))
        out = inn.functional.fused_rms_norm(x, w)
        xa = x.numpy()
        want = xa / np.sqrt((xa ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-4,
                                   atol=1e-5)
        out2 = inn.functional.fused_layer_norm(x, w, None,
                                               begin_norm_axis=1)
        want2 = (xa - xa.mean(-1, keepdims=True)) / np.sqrt(
            xa.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out2.numpy(), want2, rtol=1e-4,
                                   atol=1e-5)

    def test_swiglu(self):
        rng = np.random.default_rng(0)
        a = rng.normal(size=(2, 4)).astype(np.float32)
        b = rng.normal(size=(2, 4)).astype(np.float32)
        out = inn.functional.swiglu(paddle.to_tensor(a),
                                    paddle.to_tensor(b))
        want = a / (1 + np.exp(-a)) * b
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-5)

    def test_fused_rope_rotates(self):
        rng = np.random.default_rng(0)
        q = rng.normal(size=(2, 8, 2, 16)).astype(np.float32)
        k = rng.normal(size=(2, 8, 2, 16)).astype(np.float32)
        qo, ko, _ = inn.functional.fused_rotary_position_embedding(
            paddle.to_tensor(q), paddle.to_tensor(k))
        assert qo.shape == list(q.shape)
        # position 0 is unrotated (cos=1, sin=0)
        np.testing.assert_allclose(qo.numpy()[:, 0], q[:, 0], rtol=1e-5)
        assert not np.allclose(qo.numpy()[:, 5], q[:, 5])
        # norms preserved per pair rotation
        np.testing.assert_allclose(
            np.linalg.norm(qo.numpy(), axis=-1),
            np.linalg.norm(q, axis=-1), rtol=1e-4)

    def test_fused_encoder_layer_forward_backward(self):
        paddle.seed(0)
        layer = inn.FusedTransformerEncoderLayer(
            d_model=32, nhead=4, dim_feedforward=64, dropout_rate=0.0)
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(2, 10, 32))
            .astype(np.float32), stop_gradient=False)
        out = layer(x)
        assert out.shape == [2, 10, 32]
        out.mean().backward()
        assert layer.fused_attn.qkv.weight.grad is not None

    def test_fused_mha_matches_unfused_eval(self):
        """Eval-mode FusedMultiHeadAttention == manual sdpa with the same
        weights."""
        paddle.seed(0)
        mha = inn.FusedMultiHeadAttention(embed_dim=16, num_heads=2,
                                          dropout_rate=0.0,
                                          attn_dropout_rate=0.0)
        mha.eval()
        x = paddle.to_tensor(
            np.random.default_rng(1).normal(size=(2, 6, 16))
            .astype(np.float32))
        out = mha(x)
        assert out.shape == [2, 6, 16]
        assert np.isfinite(out.numpy()).all()


class TestFusedLNResidualDropout:
    """ref: phi/kernels/fusion/gpu/fused_layernorm_residual_dropout —
    dropout + residual + LN in one traced op (VERDICT fused-kernel row)."""

    def test_matches_composition(self):
        from paddle_tpu.incubate.nn.functional import \
            fused_layernorm_residual_dropout
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32),
                             stop_gradient=False)
        res = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        w = paddle.to_tensor(np.ones(8, np.float32), stop_gradient=False)
        b = paddle.to_tensor(np.zeros(8, np.float32))
        out, summed = fused_layernorm_residual_dropout(x, res, w, b, p=0.0)
        s = x.numpy() + res.numpy()
        mu = s.mean(-1, keepdims=True)
        var = s.var(-1, keepdims=True)
        np.testing.assert_allclose(out.numpy(),
                                   (s - mu) / np.sqrt(var + 1e-5),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(summed.numpy(), s, rtol=1e-6)
        (out ** 2).sum().backward()
        assert x.grad is not None and w.grad is not None

    def test_dropout_active_in_training(self):
        from paddle_tpu.incubate.nn.functional import \
            fused_layernorm_residual_dropout
        x = paddle.to_tensor(np.random.randn(16, 8).astype(np.float32))
        res = paddle.to_tensor(np.zeros((16, 8), np.float32))
        o1, _ = fused_layernorm_residual_dropout(x, res, p=0.5,
                                                 training=True)
        o2, _ = fused_layernorm_residual_dropout(x, res, p=0.5,
                                                 training=True)
        assert not np.allclose(o1.numpy(), o2.numpy())
        o3, _ = fused_layernorm_residual_dropout(x, res, p=0.5,
                                                 training=False)
        o4, _ = fused_layernorm_residual_dropout(x, res, p=0.5,
                                                 training=False)
        np.testing.assert_allclose(o3.numpy(), o4.numpy())

    def test_p1_grads_finite(self):
        """where()-vjp at p=1 used to emit 0/0=NaN grads (review)."""
        from paddle_tpu.incubate.nn.functional import (
            fused_dropout_add, fused_layernorm_residual_dropout)
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32),
                             stop_gradient=False)
        res = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        out, _ = fused_layernorm_residual_dropout(x, res, p=1.0,
                                                  training=True)
        (out ** 2).sum().backward()
        assert np.isfinite(x.grad.numpy()).all()
        x.clear_grad()
        fused_dropout_add(x, res, p=1.0, training=True).sum().backward()
        assert np.isfinite(x.grad.numpy()).all()
