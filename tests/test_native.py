"""Native runtime tests: flags registry, host tracer, TCPStore, mem stats.

ref analogs: test/cpp/phi (kernels/core gtest), tcp_store tests. These run
through the Python bindings of paddle_tpu/_native/native.cpp.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu._native import lib

pytestmark = pytest.mark.skipif(lib is None,
                                reason="native extension unavailable")


class TestFlags:
    def test_define_set_get(self):
        lib.flag_define("test_flag_xyz", "42", "test")
        assert lib.flag_get("test_flag_xyz") == "42"
        lib.flag_set("test_flag_xyz", "7")
        assert lib.flag_get("test_flag_xyz") == "7"
        assert "test_flag_xyz" in lib.flag_names()

    def test_unknown_flag_raises(self):
        with pytest.raises(KeyError):
            lib.flag_get("no_such_flag_abc")

    def test_python_registry_mirrors_native(self):
        import paddle_tpu as paddle
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        assert lib.flag_get("check_nan_inf") == "True"
        paddle.set_flags({"FLAGS_check_nan_inf": False})
        assert lib.flag_get("check_nan_inf") == "False"


class TestTracer:
    def test_record_and_dump(self):
        import json
        lib.tracer_start()
        t0 = lib.tracer_now()
        time.sleep(0.01)
        lib.tracer_record("op_a", t0, lib.tracer_now())
        lib.tracer_stop()
        data = json.loads(lib.tracer_dump())
        ev = data["traceEvents"]
        assert len(ev) == 1 and ev[0]["name"] == "op_a"
        assert ev[0]["dur"] >= 10_000 * 0.5  # at least ~5ms in us

    def test_profiler_api(self, tmp_path):
        import json
        import paddle_tpu.profiler as profiler
        with profiler.Profiler() as prof:
            with profiler.RecordEvent("stepA"):
                time.sleep(0.005)
        out = str(tmp_path / "trace.json")
        profiler.export_chrome_tracing(out)
        names = [e["name"] for e in
                 json.load(open(out))["traceEvents"]]
        assert "stepA" in names
        assert "stepA" in prof.summary()


class TestMemStats:
    def test_current_and_peak(self):
        lib.stat_update("test_pool", 100)
        lib.stat_update("test_pool", 200)
        lib.stat_update("test_pool", -250)
        cur, peak = lib.stat_get("test_pool")
        assert cur == 50 and peak == 300


class TestTCPStore:
    def test_set_get_add_wait_barrier(self):
        from paddle_tpu.distributed.store import TCPStore
        port = 29901
        master = TCPStore("127.0.0.1", port, is_master=True, world_size=2)
        worker = TCPStore("127.0.0.1", port, is_master=False, world_size=2)

        master.set("k", b"v1")
        assert worker.get("k") == b"v1"
        assert master.add("ctr", 5) == 5
        assert worker.add("ctr", 2) == 7

        # wait blocks until set
        res = {}

        def waiter():
            res["v"] = worker.get("late")

        th = threading.Thread(target=waiter)
        th.start()
        time.sleep(0.1)
        master.set("late", b"x")
        th.join(5)
        assert res["v"] == b"x"

        # barrier: both sides arrive concurrently
        done = []

        def arrive(store):
            store.barrier("b1")
            done.append(1)

        t1 = threading.Thread(target=arrive, args=(master,))
        t2 = threading.Thread(target=arrive, args=(worker,))
        t1.start(), t2.start()
        t1.join(5), t2.join(5)
        assert len(done) == 2

        # barrier is reusable: a second round must wait for BOTH again
        # (regression: generation-less keys let round 2 pass instantly)
        order = []

        def round2(store, tag, delay):
            time.sleep(delay)
            order.append(("arrive", tag))
            store.barrier("b1")
            order.append(("pass", tag))

        t3 = threading.Thread(target=round2, args=(master, "m", 0.0))
        t4 = threading.Thread(target=round2, args=(worker, "w", 0.3))
        t3.start(), t4.start()
        t3.join(5), t4.join(5)
        # master must not pass before worker arrives
        assert order.index(("arrive", "w")) < order.index(("pass", "m"))

        # empty value vs missing key distinction
        master.set("empty_key", b"")
        assert worker.get_nowait("empty_key") == b""
        assert worker.get_nowait("never_set_key") is None
        master.shutdown()


class TestOpRegistry:
    """Native op registry + executable cache (ref: phi KernelFactory,
    kernel_factory.h:58,240; populated from ops/ops.yaml)."""

    def test_yaml_table_registered(self):
        from paddle_tpu.ops import get_op_info, list_ops, num_ops
        assert num_ops() > 250
        info = get_op_info("matmul")
        assert info["nin"] == 2 and info["has_vjp"]
        assert info["spmd_rule"] == "matmul"
        assert "softmax" in list_ops()
        assert get_op_info("not_an_op") is None

    def test_native_and_python_mirror_agree(self):
        from paddle_tpu._native import lib
        from paddle_tpu.ops.op_registry import OP_TABLE
        if lib is None:
            pytest.skip("native lib unavailable")
        assert lib.op_count() == len(OP_TABLE)
        d = lib.op_lookup("flash_attention")
        assert d["spmd_rule"] == "flash_attention"

    def test_exec_cache_roundtrip_and_stats(self):
        from paddle_tpu._native import lib
        if lib is None:
            pytest.skip("native lib unavailable")
        lib.exec_cache_clear()
        fn = lambda x: x * 2
        assert lib.exec_cache_get("k1") is None
        lib.exec_cache_put("k1", fn)
        assert lib.exec_cache_get("k1") is fn
        hits, misses, size = lib.exec_cache_stats()
        assert (hits, misses, size) == (1, 1, 1)
        # replacing the entry must not leak or crash (refcount handling)
        lib.exec_cache_put("k1", lambda x: x)
        assert lib.exec_cache_get("k1") is not fn
        lib.exec_cache_clear()
        assert lib.exec_cache_stats() == (0, 0, 0)


class TestPredictorExecCacheSharing:
    def test_same_artifact_shares_jitted_callable(self, tmp_path):
        import numpy as np
        import paddle_tpu.nn as nn
        from paddle_tpu import inference
        from paddle_tpu._native import lib
        if lib is None:
            pytest.skip("native lib unavailable")

        import paddle_tpu as paddle
        from paddle_tpu.vision.models import LeNet
        paddle.seed(0)

        m = LeNet()
        path = str(tmp_path / "m")
        inference.save_inference_model(path, m)
        p1 = inference.Predictor(inference.Config(path))
        p2 = inference.Predictor(inference.Config(path))
        assert p1._jitted is p2._jitted, "exec cache did not share"
        x = np.ones((1, 1, 28, 28), np.float32)
        np.testing.assert_allclose(p1.run(x)[0], p2.run(x)[0])


class TestRegistryOnHotPath:
    """VERDICT round-1 weak item 2: the native OpRegistry serves eager
    dispatch (has_vjp gating, arity validation, dispatch counting), not
    just introspection."""

    def test_dispatch_counts_grow(self):
        from paddle_tpu.ops.op_registry import dispatch_counts
        t = paddle.to_tensor(np.ones(3, np.float32))
        before = dispatch_counts().get("add", 0)
        _ = t + t
        _ = t + t
        assert dispatch_counts().get("add", 0) >= before + 2

    def test_sampler_ops_skip_tape(self):
        # bernoulli is has_vjp=false in ops.yaml: output carries no node
        # even when the input requires grad
        x = paddle.to_tensor(np.full((4,), 0.5, np.float32),
                             stop_gradient=False)
        out = paddle.bernoulli(x)
        assert out._node is None
        assert out.stop_gradient

    def test_arity_violation_raises(self):
        from paddle_tpu.core.autograd import apply_op, _op_gate_cache
        _op_gate_cache.pop("matmul_arity_probe", None)
        from paddle_tpu.ops.op_registry import OP_TABLE
        OP_TABLE["matmul_arity_probe"] = {
            "module": "linalg", "nin": 2, "nargs": 2, "has_vjp": True,
            "spmd_rule": ""}
        with pytest.raises(TypeError, match="at most 2"):
            apply_op(lambda a, b, c: a, paddle.to_tensor(1.0),
                     paddle.to_tensor(1.0), paddle.to_tensor(1.0),
                     op_name="matmul_arity_probe")
        OP_TABLE.pop("matmul_arity_probe")
        _op_gate_cache.pop("matmul_arity_probe", None)

    def test_variadic_ops_uncapped(self):
        ts = [paddle.to_tensor(np.ones((2, 2), np.float32))
              for _ in range(8)]
        assert paddle.concat(ts, axis=0).shape == [16, 2]
        assert paddle.stack(ts).shape == [8, 2, 2]
