"""MoE + expert-parallelism tests.

ref: the reference's MoE tests live under test/collective/fleet (moe
dispatch via global_scatter/global_gather); parity gate = expert-parallel
run matches the single-device run.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import ProcessMesh
from paddle_tpu.distributed.dist_train import DistTrainStep
from paddle_tpu.incubate.moe import MoELayer, _gshard_dispatch
from paddle_tpu.models import (ErnieMoEConfig, ErnieMoEForCausalLM,
                               LlamaPretrainingCriterion)


class TestDispatch:
    def test_combine_weights_match_topk_probs(self, rng):
        import jax
        import jax.numpy as jnp
        logits = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
        # ample capacity: nothing dropped, combine mass == top-2 prob mass
        combine, dispatch, aux = _gshard_dispatch(logits, 2, capacity=32)
        probs = jax.nn.softmax(logits, -1)
        s = np.asarray(combine.sum(axis=(1, 2)))
        top2 = np.asarray(jnp.sort(probs, axis=-1)[:, -2:].sum(-1))
        np.testing.assert_allclose(s, top2, atol=1e-5)
        assert float(aux) > 0

    def test_no_slot_collisions(self, rng):
        """Each dispatch slot receives at most one token (regression: the
        per-k cumsum used to restart at 0, stacking 2nd-choice tokens onto
        1st-choice slots)."""
        import jax.numpy as jnp
        logits = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
        _, dispatch, _ = _gshard_dispatch(logits, 2, capacity=32)
        per_slot = np.asarray(dispatch.sum(axis=0))  # [E, C]
        assert per_slot.max() <= 1

    def test_capacity_drops_tokens(self, rng):
        import jax.numpy as jnp
        # all tokens prefer expert 0; capacity 2 keeps only 2
        logits = jnp.tile(jnp.asarray([[10.0, 0, 0, 0]], jnp.float32),
                          (8, 1))
        combine, dispatch, _ = _gshard_dispatch(logits, 1, capacity=2)
        kept = np.asarray(dispatch[:, 0].any(axis=-1))
        assert kept.sum() == 2

    def test_topk_clamped_to_num_experts(self, rng):
        import jax
        import jax.numpy as jnp
        logits = jnp.asarray(rng.normal(size=(8, 1)), jnp.float32)
        combine, _, _ = _gshard_dispatch(logits, 2, capacity=16)
        # single expert, top_k=2: every token contributes prob 1.0 once
        np.testing.assert_allclose(np.asarray(combine.sum(axis=(1, 2))),
                                   np.ones(8), atol=1e-5)

    def test_moe_layer_matches_dense_reference(self, rng):
        """With ample capacity, MoELayer == dense per-token top-2 mixture."""
        import jax
        import jax.numpy as jnp
        x_np = rng.normal(size=(1, 16, 8)).astype(np.float32)
        moe = MoELayer(8, 16, 4, top_k=2, capacity_factor=100.0,
                       activation="gelu")
        out = moe(paddle.to_tensor(x_np)).numpy()

        tokens = jnp.asarray(x_np.reshape(16, 8))
        probs = jax.nn.softmax(
            tokens @ moe.gate.weight._data.astype(jnp.float32), -1)
        dense = np.zeros((16, 8), np.float32)
        order = np.argsort(-np.asarray(probs), axis=-1)
        for t in range(16):
            for e in order[t, :2]:
                h = jax.nn.gelu(tokens[t] @ moe.w_in._data[e])
                dense[t] += float(probs[t, e]) * np.asarray(
                    h @ moe.w_out._data[e])
        np.testing.assert_allclose(out.reshape(16, 8), dense, atol=1e-4)


class TestMoELayer:
    def test_forward_backward(self, rng):
        x = paddle.to_tensor(rng.normal(size=(2, 8, 16)).astype(np.float32),
                             stop_gradient=False)
        moe = MoELayer(16, 32, 4, top_k=2)
        y = moe(x)
        assert y.shape == [2, 8, 16]
        (y * y).mean().backward()
        assert moe.w_in.grad is not None
        assert moe.gate.weight.grad is not None
        assert x.grad is not None
        assert moe.aux_loss is not None

    def test_switch_and_naive_gates(self, rng):
        x = paddle.to_tensor(rng.normal(size=(1, 8, 16)).astype(np.float32))
        for gate in ("switch", "naive"):
            y = MoELayer(16, 32, 4, gate=gate)(x)
            assert y.shape == [1, 8, 16]


class TestExpertParallel:
    def test_ep_sharded_matches_single(self, rng):
        """Expert-parallel training step == unsharded step (the reference's
        acc-align contract for its alltoall dispatch path)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        ids_np = rng.integers(0, 128, (4, 16)).astype(np.int32)

        def run(shard):
            paddle.seed(0)
            m = ErnieMoEForCausalLM(ErnieMoEConfig.tiny())
            crit = LlamaPretrainingCriterion()

            def loss_fn(logits, labels):
                loss = crit(logits, labels)
                aux = m.total_aux_loss()
                return loss if aux is None else loss + aux

            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters())
            data_sharding = None
            if shard:
                mesh = ProcessMesh(np.arange(8).reshape(2, 4),
                                   dim_names=["dp", "ep"])
                m.shard_experts(mesh, "ep")
                data_sharding = NamedSharding(mesh.to_jax_mesh(),
                                              P("dp", None))
            step = DistTrainStep(m, loss_fn, opt,
                                 data_sharding=data_sharding)
            return [float(step(ids_np, ids_np)) for _ in range(3)]

        single = run(False)
        ep = run(True)
        assert ep[-1] < ep[0]
        np.testing.assert_allclose(single, ep, rtol=2e-4)


class TestIndexDispatch:
    """Round-2 scalable dispatch (incubate.moe_dispatch): gather/scatter
    index tables + grouped matmul, acc-aligned against the dense one-hot
    oracle (VERDICT item 5)."""

    def test_forward_matches_dense_oracle(self, rng):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.incubate.moe_dispatch import moe_forward_indices
        T, E, C, H, F = 64, 8, 12, 16, 32
        tokens = jnp.asarray(rng.normal(size=(T, H)).astype(np.float32))
        gw = jnp.asarray(rng.normal(size=(H, E)).astype(np.float32))
        wi = jnp.asarray(rng.normal(size=(E, H, F)).astype(np.float32)) * .1
        wo = jnp.asarray(rng.normal(size=(E, F, H)).astype(np.float32)) * .1
        out_i, aux_i = moe_forward_indices(tokens, gw, wi, wo, 2, C,
                                           jax.nn.gelu)
        combine, dispatch, aux_d = _gshard_dispatch(tokens @ gw, 2, C)
        xs = jnp.einsum("tec,th->ech", dispatch.astype(jnp.float32), tokens)
        hdn = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", xs, wi))
        ys = jnp.einsum("ecf,efh->ech", hdn, wo)
        out_d = jnp.einsum("tec,ech->th", combine, ys)
        np.testing.assert_allclose(np.asarray(out_i), np.asarray(out_d),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(aux_i), float(aux_d), rtol=1e-6)

    def test_moe_layer_index_vs_dense_mode(self, rng):
        x_np = rng.normal(size=(2, 16, 8)).astype(np.float32)
        paddle.seed(3)
        dense = MoELayer(8, 16, 4, top_k=2, capacity_factor=2.0,
                         dispatch_mode="dense")
        paddle.seed(3)
        index = MoELayer(8, 16, 4, top_k=2, capacity_factor=2.0,
                         dispatch_mode="index")
        out_d = dense(paddle.to_tensor(x_np))
        out_i = index(paddle.to_tensor(x_np))
        np.testing.assert_allclose(out_i.numpy(), out_d.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_auto_mode_routes_by_token_count(self, rng, monkeypatch):
        """dispatch_mode='auto': dense algebra below the crossover,
        index dispatch above — outputs match either way."""
        from paddle_tpu.incubate import moe as moe_mod
        x_np = rng.normal(size=(2, 16, 8)).astype(np.float32)
        paddle.seed(3)
        auto = MoELayer(8, 16, 4, top_k=2, capacity_factor=2.0,
                        dispatch_mode="auto")
        paddle.seed(3)
        dense = MoELayer(8, 16, 4, top_k=2, capacity_factor=2.0,
                         dispatch_mode="dense")
        # 32 tokens < crossover: auto takes the dense path
        out_a = auto(paddle.to_tensor(x_np))
        np.testing.assert_allclose(
            out_a.numpy(), dense(paddle.to_tensor(x_np)).numpy(),
            rtol=1e-5, atol=1e-6)
        # force the crossover below the batch: auto takes the index path
        monkeypatch.setattr(moe_mod, "_AUTO_DENSE_TOKENS", 16)
        out_i = auto(paddle.to_tensor(x_np))
        np.testing.assert_allclose(
            out_i.numpy(), out_a.numpy(), rtol=1e-4, atol=1e-5)

    def test_index_mode_trains(self, rng):
        x = paddle.to_tensor(rng.normal(size=(2, 8, 16)).astype(np.float32),
                             stop_gradient=False)
        moe = MoELayer(16, 32, 4, top_k=2, dispatch_mode="index")
        y = moe(x)
        (y * y).mean().backward()
        assert moe.w_in.grad is not None
        assert float(np.abs(moe.w_in.grad.numpy()).max()) > 0
        assert x.grad is not None

    def test_grouped_matmul_matches_reference(self, rng):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.grouped_matmul import (
            grouped_matmul, grouped_matmul_reference)
        E, K, N = 4, 16, 24
        gs = jnp.asarray([5, 0, 7, 4], jnp.int32)   # sums to 16 < T=20
        T = 20
        lhs = jnp.asarray(rng.normal(size=(T, K)).astype(np.float32))
        rhs = jnp.asarray(rng.normal(size=(E, K, N)).astype(np.float32))
        # CPU path: both use the dense fallback; assert the oracle itself
        out = grouped_matmul(lhs, rhs, gs)
        ref = grouped_matmul_reference(lhs, rhs, gs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5)
        # rows past sum(group_sizes) (padding) must be zero
        bounds = int(np.asarray(gs).sum())
        assert bounds < T
        np.testing.assert_allclose(np.asarray(ref)[bounds:], 0)
        assert np.abs(np.asarray(ref)[:bounds]).max() > 0
        # per-row check against the expert each row belongs to
        row_expert = np.repeat(np.arange(E), np.asarray(gs))
        for r in range(bounds):
            np.testing.assert_allclose(
                np.asarray(ref)[r],
                np.asarray(lhs)[r] @ np.asarray(rhs)[row_expert[r]],
                rtol=1e-4, atol=1e-5)

    def test_grouped_matmul_rejects_unsorted_tile_ids(self, rng):
        """dRHS backward requires contiguous per-expert tiles — a
        non-monotonic caller-supplied tile map must fail loudly, not
        silently corrupt weight grads (ADVICE r2)."""
        import jax.numpy as jnp
        import pytest
        from paddle_tpu.ops.pallas.grouped_matmul import grouped_matmul
        T, K, N, E = 256, 128, 128, 2
        lhs = jnp.zeros((T, K), jnp.float32)
        rhs = jnp.zeros((E, K, N), jnp.float32)
        bad_ids = jnp.asarray([1, 0], jnp.int32)  # scattered map
        with pytest.raises(ValueError, match="non-decreasing"):
            grouped_matmul(lhs, rhs, jnp.asarray([128, 128], jnp.int32),
                           tile_ids=bad_ids)

    def test_ep_sharded_index_dispatch_lowers_to_alltoall(self, rng):
        """The ep-sharded index-dispatch program must contain all-to-all
        (or equivalent resharding collectives) in the compiled HLO —
        the reference's global_scatter contract (VERDICT: inspect HLO)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from paddle_tpu.incubate.moe_dispatch import moe_forward_indices

        E, C, H, F, T = 8, 16, 32, 64, 128
        mesh = Mesh(np.asarray(jax.devices()[:8]), ("ep",))
        gw = jnp.asarray(rng.normal(size=(H, E)).astype(np.float32))
        wi = jax.device_put(
            jnp.asarray(rng.normal(size=(E, H, F)).astype(np.float32)),
            NamedSharding(mesh, P("ep", None, None)))
        wo = jax.device_put(
            jnp.asarray(rng.normal(size=(E, F, H)).astype(np.float32)),
            NamedSharding(mesh, P("ep", None, None)))
        tokens = jnp.asarray(rng.normal(size=(T, H)).astype(np.float32))

        fn = jax.jit(lambda t, g, a, b: moe_forward_indices(
            t, g, a, b, 2, C, jax.nn.gelu)[0])
        hlo = fn.lower(tokens, gw, wi, wo).compile().as_text()
        assert ("all-to-all" in hlo or "all-gather" in hlo or
                "collective-permute" in hlo), \
            "expected cross-device collectives in the ep-sharded program"
        out = np.asarray(fn(tokens, gw, wi, wo))
        # numerics unchanged by sharding
        ref = np.asarray(jax.jit(lambda t, g, a, b: moe_forward_indices(
            t, g, a, b, 2, C, jax.nn.gelu)[0])(
            tokens, gw, jax.device_get(wi), jax.device_get(wo)))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)
