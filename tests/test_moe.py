"""MoE + expert-parallelism tests.

ref: the reference's MoE tests live under test/collective/fleet (moe
dispatch via global_scatter/global_gather); parity gate = expert-parallel
run matches the single-device run.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import ProcessMesh
from paddle_tpu.distributed.dist_train import DistTrainStep
from paddle_tpu.incubate.moe import MoELayer, _gshard_dispatch
from paddle_tpu.models import (ErnieMoEConfig, ErnieMoEForCausalLM,
                               LlamaPretrainingCriterion)


class TestDispatch:
    def test_combine_weights_match_topk_probs(self, rng):
        import jax
        import jax.numpy as jnp
        logits = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
        # ample capacity: nothing dropped, combine mass == top-2 prob mass
        combine, dispatch, aux = _gshard_dispatch(logits, 2, capacity=32)
        probs = jax.nn.softmax(logits, -1)
        s = np.asarray(combine.sum(axis=(1, 2)))
        top2 = np.asarray(jnp.sort(probs, axis=-1)[:, -2:].sum(-1))
        np.testing.assert_allclose(s, top2, atol=1e-5)
        assert float(aux) > 0

    def test_no_slot_collisions(self, rng):
        """Each dispatch slot receives at most one token (regression: the
        per-k cumsum used to restart at 0, stacking 2nd-choice tokens onto
        1st-choice slots)."""
        import jax.numpy as jnp
        logits = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
        _, dispatch, _ = _gshard_dispatch(logits, 2, capacity=32)
        per_slot = np.asarray(dispatch.sum(axis=0))  # [E, C]
        assert per_slot.max() <= 1

    def test_capacity_drops_tokens(self, rng):
        import jax.numpy as jnp
        # all tokens prefer expert 0; capacity 2 keeps only 2
        logits = jnp.tile(jnp.asarray([[10.0, 0, 0, 0]], jnp.float32),
                          (8, 1))
        combine, dispatch, _ = _gshard_dispatch(logits, 1, capacity=2)
        kept = np.asarray(dispatch[:, 0].any(axis=-1))
        assert kept.sum() == 2

    def test_topk_clamped_to_num_experts(self, rng):
        import jax
        import jax.numpy as jnp
        logits = jnp.asarray(rng.normal(size=(8, 1)), jnp.float32)
        combine, _, _ = _gshard_dispatch(logits, 2, capacity=16)
        # single expert, top_k=2: every token contributes prob 1.0 once
        np.testing.assert_allclose(np.asarray(combine.sum(axis=(1, 2))),
                                   np.ones(8), atol=1e-5)

    def test_moe_layer_matches_dense_reference(self, rng):
        """With ample capacity, MoELayer == dense per-token top-2 mixture."""
        import jax
        import jax.numpy as jnp
        x_np = rng.normal(size=(1, 16, 8)).astype(np.float32)
        moe = MoELayer(8, 16, 4, top_k=2, capacity_factor=100.0,
                       activation="gelu")
        out = moe(paddle.to_tensor(x_np)).numpy()

        tokens = jnp.asarray(x_np.reshape(16, 8))
        probs = jax.nn.softmax(
            tokens @ moe.gate.weight._data.astype(jnp.float32), -1)
        dense = np.zeros((16, 8), np.float32)
        order = np.argsort(-np.asarray(probs), axis=-1)
        for t in range(16):
            for e in order[t, :2]:
                h = jax.nn.gelu(tokens[t] @ moe.w_in._data[e])
                dense[t] += float(probs[t, e]) * np.asarray(
                    h @ moe.w_out._data[e])
        np.testing.assert_allclose(out.reshape(16, 8), dense, atol=1e-4)


class TestMoELayer:
    def test_forward_backward(self, rng):
        x = paddle.to_tensor(rng.normal(size=(2, 8, 16)).astype(np.float32),
                             stop_gradient=False)
        moe = MoELayer(16, 32, 4, top_k=2)
        y = moe(x)
        assert y.shape == [2, 8, 16]
        (y * y).mean().backward()
        assert moe.w_in.grad is not None
        assert moe.gate.weight.grad is not None
        assert x.grad is not None
        assert moe.aux_loss is not None

    def test_switch_and_naive_gates(self, rng):
        x = paddle.to_tensor(rng.normal(size=(1, 8, 16)).astype(np.float32))
        for gate in ("switch", "naive"):
            y = MoELayer(16, 32, 4, gate=gate)(x)
            assert y.shape == [1, 8, 16]


class TestExpertParallel:
    def test_ep_sharded_matches_single(self, rng):
        """Expert-parallel training step == unsharded step (the reference's
        acc-align contract for its alltoall dispatch path)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        ids_np = rng.integers(0, 128, (4, 16)).astype(np.int32)

        def run(shard):
            paddle.seed(0)
            m = ErnieMoEForCausalLM(ErnieMoEConfig.tiny())
            crit = LlamaPretrainingCriterion()

            def loss_fn(logits, labels):
                loss = crit(logits, labels)
                aux = m.total_aux_loss()
                return loss if aux is None else loss + aux

            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters())
            data_sharding = None
            if shard:
                mesh = ProcessMesh(np.arange(8).reshape(2, 4),
                                   dim_names=["dp", "ep"])
                m.shard_experts(mesh, "ep")
                data_sharding = NamedSharding(mesh.to_jax_mesh(),
                                              P("dp", None))
            step = DistTrainStep(m, loss_fn, opt,
                                 data_sharding=data_sharding)
            return [float(step(ids_np, ids_np)) for _ in range(3)]

        single = run(False)
        ep = run(True)
        assert ep[-1] < ep[0]
        np.testing.assert_allclose(single, ep, rtol=2e-4)
