"""paddle_tpu.analysis: program auditor, source linter, lock checker.

Seeded-bug fixtures (ISSUE 6 acceptance): a synthetic use-after-donate,
an injected host sync in a fused chain, a cache-key churn loop and a
deliberate lock-order cycle — each detected by its exact rule id — plus
a zero-false-positive capture audit over a clean llama train step whose
report enumerates every flush boundary with reason AND origin.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import audit, lint, report
from paddle_tpu.analysis.auditor import Auditor
from paddle_tpu.analysis.diagnostics import RULES, Diagnostic
from paddle_tpu.analysis.lint import lint_source
from paddle_tpu.analysis import locks as alocks
from paddle_tpu.analysis.report import self_check
from paddle_tpu.core.flags import set_flags


def _rules(diags):
    return {d.rule for d in diags}


# ---------------------------------------------------------------------------
# lint engine (AST rules on seeded source)
# ---------------------------------------------------------------------------

class TestLintEngine:
    def test_bare_except_detected(self):
        diags = lint_source(
            "def f():\n"
            "    try:\n"
            "        run()\n"
            "    except:\n"
            "        pass\n")
        assert "PTL004" in _rules(diags)

    def test_host_sync_detected(self):
        diags = lint_source(
            "def f(t):\n"
            "    return t.numpy()\n")
        assert "PTL001" in _rules(diags)

    def test_item_on_chained_call_not_flagged(self):
        # np.asarray(...).item() is a host->host numpy idiom, not a
        # device sync — the receiver heuristic must skip it
        diags = lint_source(
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x).item()\n")
        assert "PTL001" not in _rules(diags)
        diags = lint_source(
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.cumsum(x).tolist()\n")
        assert "PTL001" not in _rules(diags)

    def test_item_on_chained_device_call_flagged(self):
        # loss.mean().item() IS a device sync — the numpy-idiom
        # exemption must not swallow chained device calls
        diags = lint_source(
            "def f(loss):\n"
            "    return loss.mean().item()\n")
        assert "PTL001" in _rules(diags)

    def test_unguarded_registry_mutation_detected(self):
        diags = lint_source(
            "CACHE = {}\n"
            "def evict():\n"
            "    CACHE.clear()\n")
        assert "PTL003" in _rules(diags)

    def test_guarded_mutation_not_flagged(self):
        diags = lint_source(
            "import threading\n"
            "CACHE = {}\n"
            "_lock = threading.Lock()\n"
            "def evict():\n"
            "    with _lock:\n"
            "        CACHE.clear()\n")
        assert "PTL003" not in _rules(diags)

    def test_memo_insert_not_flagged(self):
        # single-assignment memo inserts are GIL-atomic by design
        diags = lint_source(
            "CACHE = {}\n"
            "def put(k, v):\n"
            "    CACHE[k] = v\n")
        assert "PTL003" not in _rules(diags)

    def test_del_while_sweeping_detected(self):
        # the exact pattern the alias registry had before PR 6
        diags = lint_source(
            "REG = {}\n"
            "def sweep():\n"
            "    for k in [k for k, d in REG.items() if not d]:\n"
            "        del REG[k]\n")
        assert "PTL003" in _rules(diags)

    def test_inline_pragma_suppresses(self, tmp_path):
        p = tmp_path / "snippet.py"
        p.write_text("CACHE = {}\n"
                     "def evict():\n"
                     "    CACHE.clear()  # lint-allow: PTL003 teardown\n")
        r = lint(paths=[str(p)])
        assert not [d for d in r.diagnostics if d.rule == "PTL003"]
        assert any(d.rule == "PTL003" for d, _ in r.suppressed)

    def test_unknown_rule_defaults_severity(self):
        d = Diagnostic("PTL004", "x.py:1", "m")
        assert d.severity == RULES["PTL004"].severity == "error"


class TestLintRepo:
    def test_flag_read_facts_cover_wired_flags(self):
        """The flags PR 6 wired (benchmark, retain_grad_for_all_tensor)
        must no longer appear as PTL002 findings."""
        r = lint()
        locs = [d.message for d in r.diagnostics if d.rule == "PTL002"]
        assert not any("benchmark" in m for m in locs)
        assert not any("retain_grad_for_all_tensor" in m for m in locs)

    def test_allowlist_entries_all_match_something(self):
        """A stale allowlist entry (site fixed but entry kept) is dead
        weight — every entry must still suppress at least one raw
        finding."""
        from paddle_tpu.analysis.allowlist import ALLOWLIST
        raw = lint(use_allowlist=False)
        import fnmatch
        for rule, pattern, _why in ALLOWLIST:
            hit = any(
                d.rule == rule and (
                    fnmatch.fnmatch(d.location.partition(":")[0], pattern)
                    or fnmatch.fnmatch(d.location, pattern)
                    or fnmatch.fnmatch(d.message, pattern))
                for d in raw.diagnostics)
            assert hit, (f"allowlist entry ({rule}, {pattern!r}) matches "
                         f"no finding — fixed site? delete the entry")


# ---------------------------------------------------------------------------
# program auditor: seeded bugs
# ---------------------------------------------------------------------------

class TestAuditorSeededBugs:
    def test_host_sync_in_fused_chain(self):
        """An injected .numpy() mid-chain must surface as PTA001 AND as
        a host_read flush whose origin points at THIS file."""
        def step():
            x = paddle.to_tensor(np.ones((8, 8), np.float32))
            y = paddle.add(paddle.multiply(x, 3.0), 1.0)
            y.numpy()                      # seeded host sync
            z = paddle.multiply(y, 2.0)
            return z.numpy()

        rep = audit(step, warmup=1)
        assert any(d.rule == "PTA001" for d in rep.diagnostics)
        host_reads = [f for f in rep.flushes if f["reason"] == "host_read"]
        assert host_reads, rep.flushes
        assert any("test_analysis.py" in f["origin"] for f in host_reads)
        assert any("test_analysis.py" in s["origin"] for s in rep.syncs)

    def test_use_after_donate(self):
        """A live handle wrapping a deleted (donated) buffer must be
        found by the post-run sweep as PTA002."""
        holder = []

        def step():
            x = paddle.to_tensor(np.ones((8,), np.float32))
            holder.append(x)
            # simulate what XLA donation does to the input buffer: the
            # handle keeps pointing at a deleted array
            x._data.delete()

        rep = audit(step, warmup=0)
        holder.clear()
        assert any(d.rule == "PTA002" for d in rep.diagnostics), \
            [d.to_dict() for d in rep.diagnostics]
        assert rep.use_after_donate

    def test_read_of_donated_buffer_attributed(self):
        """Reading a deleted buffer through .numpy() is caught AT the
        read with call-site attribution (before the crash)."""
        def step():
            x = paddle.to_tensor(np.ones((4,), np.float32))
            x._data.delete()
            try:
                x.numpy()
            except Exception:
                pass  # the read itself fails; the audit still records it

        rep = audit(step, warmup=0)
        uad = [d for d in rep.diagnostics if d.rule == "PTA002"]
        assert uad
        assert any("test_analysis.py" in d.location for d in uad)

    def test_crashing_step_still_ships_the_report(self):
        """A real use-after-donate CRASHES the measured run; the audit's
        whole point is the attribution recorded up to the crash — it
        rides the exception as .capture_report."""
        def step():
            x = paddle.to_tensor(np.ones((4,), np.float32))
            x.numpy()                      # recorded sync
            x._data.delete()
            x.numpy()                      # raises on the deleted buffer

        with pytest.raises(Exception) as ei:
            audit(step, warmup=0)
        rep = getattr(ei.value, "capture_report", None)
        assert rep is not None
        assert any(d.rule == "PTA001" for d in rep.diagnostics)
        assert any(d.rule == "PTA002" for d in rep.diagnostics)

    def test_recompile_churn_loop(self):
        """A shape-polymorphic call site keeps compiling in the measured
        window -> PTA003 naming the shape churn."""
        from paddle_tpu.core import fusion
        fusion.clear_cache()  # churn needs a cold program cache: other
        # tests (e.g. test_capture_plan) use the same chain structures

        def churn():
            for n in range(3, 9):
                x = paddle.to_tensor(np.ones((n,), np.float32))
                y = paddle.add(paddle.multiply(x, 2.0), 1.0)
                y.numpy()

        rep = audit(churn, warmup=1)
        churn_d = [d for d in rep.diagnostics if d.rule == "PTA003"]
        assert churn_d, [d.to_dict() for d in rep.diagnostics]
        assert any("shape-polymorphic" in d.message for d in churn_d)

    def test_steady_state_chain_is_churn_free(self):
        """Same shapes every iteration: after warmup the measured run
        must be compile-free (no PTA003 false positive)."""
        def step():
            x = paddle.to_tensor(np.ones((8,), np.float32))
            y = paddle.add(paddle.multiply(x, 2.0), 1.0)
            y.numpy()

        rep = audit(step, warmup=2)
        assert not [d for d in rep.diagnostics if d.rule == "PTA003"], \
            [d.to_dict() for d in rep.diagnostics]
        assert not rep.fusion_compiles


# ---------------------------------------------------------------------------
# program auditor: clean llama train step (zero false positives)
# ---------------------------------------------------------------------------

class TestAuditorLlamaStep:
    def _fit_step(self):
        from paddle_tpu.hapi import Model
        from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                       LlamaPretrainingCriterion)
        paddle.seed(0)
        net = LlamaForCausalLM(LlamaConfig.tiny())
        m = Model(net)
        m.prepare(optimizer=paddle.optimizer.AdamW(
            learning_rate=1e-3, parameters=net.parameters()),
            loss=LlamaPretrainingCriterion())
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 128, (2, 16)).astype(np.int64)

        def step():
            m.train_batch([ids], [ids])

        return step

    def test_capture_report_enumerates_flushes_no_false_positives(self):
        """The EAGER planning input (Fusion III implemented the plan;
        FLAGS_sot_capture=0 pins that the per-chain path the planner
        audited still behaves and attributes as before)."""
        step = self._fit_step()
        set_flags({"FLAGS_sot_capture": 0})
        try:
            rep = audit(step, warmup=3)
        finally:
            set_flags({"FLAGS_sot_capture": 1})
        # the capture report enumerates flush boundaries with reason
        # AND origin — the Fusion III planning input
        assert rep.flushes, "an eager llama train step must flush"
        assert all(f["reason"] for f in rep.flushes)
        assert all(f["origin"] != "<unknown>" for f in rep.flushes)
        assert rep.flush_sites(), "aggregated top-N flush sites"
        # zero false positives on clean code: no use-after-donate, no
        # steady-state recompile churn
        assert not [d for d in rep.diagnostics if d.rule == "PTA002"], \
            [d.to_dict() for d in rep.diagnostics]
        assert not [d for d in rep.diagnostics if d.rule == "PTA003"], \
            [d.to_dict() for d in rep.diagnostics]
        # the loss fetch is HOISTED out of train_batch (Fusion III):
        # even the eager step is sync-free in its measured window
        assert not [d for d in rep.diagnostics if d.rule == "PTA001"], \
            [d.to_dict() for d in rep.diagnostics]


# ---------------------------------------------------------------------------
# lock-order checker
# ---------------------------------------------------------------------------

class TestWiredFlags:
    """Behavioral contracts for the two flags PR 6 wired (a lint-absence
    check alone can't prove the documented behavior exists — PTL002's
    own lesson)."""

    def test_benchmark_flag_forces_eager_dispatch(self):
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        y = paddle.add(paddle.multiply(x, 2.0), 1.0)
        assert y._lazy is not None  # normally: deferred into the DAG
        y.numpy()
        set_flags({"FLAGS_benchmark": 1})
        try:
            z = paddle.add(paddle.multiply(x, 2.0), 1.0)
            # sync-after-each-op requires each op to actually dispatch
            assert z._lazy is None
        finally:
            set_flags({"FLAGS_benchmark": 0})

    def test_retain_all_flag_populates_interior_and_root_grads(self):
        def run():
            x = paddle.to_tensor(np.ones(3, np.float32),
                                 stop_gradient=False)
            h = paddle.multiply(x, 2.0)
            loss = h.sum()
            loss.backward()
            return x, h, loss

        x0, h0, l0 = run()
        assert x0.grad is not None and h0.grad is None and l0.grad is None
        set_flags({"FLAGS_retain_grad_for_all_tensor": 1})
        try:
            x1, h1, l1 = run()
        finally:
            set_flags({"FLAGS_retain_grad_for_all_tensor": 0})
        assert x1.grad is not None
        np.testing.assert_allclose(h1.grad.numpy(), np.ones(3))
        np.testing.assert_allclose(l1.grad.numpy(), 1.0)


class TestLockChecker:
    def test_seeded_cycle_detected(self):
        aud = alocks.LockAuditor()
        a, b = aud.lock("A"), aud.lock("B")

        def ab():
            with a, b:
                pass

        def ba():
            with b, a:
                pass

        ab()
        t = threading.Thread(target=ba)
        t.start()
        t.join()
        diags = aud.diagnostics()
        assert any(d.rule == "PTK001" for d in diags)
        assert aud.cycles()
        # summary() composes cycles + bookkeeping without deadlocking
        assert aud.summary()["cycles"] == ["A -> B -> A"]

    def test_cross_thread_release_no_phantom_hold(self):
        """threading.Lock handoff: acquired on one thread, released on
        another — the acquirer's hold must be evicted, not poison every
        later nesting edge on that thread."""
        aud = alocks.LockAuditor()
        lk, other = aud.lock("L"), aud.lock("X")
        lk.acquire()
        t = threading.Thread(target=lk.release)
        t.start()
        t.join()
        assert not aud.held_now()
        with other:
            pass
        assert ("L", "X") not in aud.edges

    def test_condition_on_patched_rlock_reentrant_wait(self):
        """threading.Condition probes _release_save/_acquire_restore on
        its lock; the shim must delegate them or a reentrant holder's
        wait() releases one level and deadlocks."""
        done = []
        with alocks.instrument():
            cond = threading.Condition()   # patched RLock underneath

            def waiter():
                with cond:
                    with cond:             # reentrant hold
                        cond.wait(timeout=10)
                        done.append(True)

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.1)
            with cond:
                cond.notify_all()
            t.join(timeout=10)
            assert not t.is_alive(), "reentrant Condition.wait deadlocked"
        assert done

    def test_closed_auditor_degrades_to_plain_lock(self):
        """Objects built under instrument() keep their locks for life;
        after the context exits they must stop recording (and paying
        the stack walk) entirely."""
        with alocks.instrument(patch_threading=False) as aud:
            lk = alocks.make_lock("survivor")
            with lk:
                pass
        n = aud.acquisitions.get("survivor")
        with lk:
            pass
        assert aud.acquisitions.get("survivor") == n

    def test_consistent_order_is_clean(self):
        aud = alocks.LockAuditor()
        a, b = aud.lock("A"), aud.lock("B")
        for _ in range(3):
            with a, b:
                pass
        assert not aud.cycles()
        assert not [d for d in aud.diagnostics() if d.rule == "PTK001"]

    def test_device_op_under_lock_detected(self):
        with alocks.instrument(patch_threading=False) as aud:
            lk = aud.lock("test.device_hold")
            with lk:
                x = paddle.to_tensor(np.ones((4,), np.float32))
                y = paddle.add(paddle.multiply(x, 2.0), 1.0)
                y.numpy()   # fusion flush while holding the lock
        diags = aud.diagnostics()
        assert any(d.rule == "PTK002" and "fusion_flush" in d.message
                   for d in diags), [d.to_dict() for d in diags]

    def test_make_lock_routes_to_active_auditor(self):
        from paddle_tpu.analysis.locks import make_lock
        plain = make_lock("x")
        assert not isinstance(plain, alocks.InstrumentedLock)
        with alocks.instrument(patch_threading=False):
            inst = make_lock("x")
            assert isinstance(inst, alocks.InstrumentedLock)


class _MemStore:
    """Minimal in-memory store surface for ElasticManager."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def get_nowait(self, k):
        return self._d.get(k)

    def set(self, k, v):
        with self._lock:
            self._d[k] = v

    def add(self, k, n):
        with self._lock:
            v = int(self._d.get(k, 0)) + n
            self._d[k] = v
            return v

    def delete(self, k):
        with self._lock:
            self._d.pop(k, None)


class TestSubsystemLockOrder:
    """PR 2's threads had never been order-checked. This is the
    regression test proving the ordering is clean (the satellite's
    'if none reproduce' branch): async checkpoint, serving drain and
    elastic watch run under full lock instrumentation and must produce
    no lock-order cycle."""

    def test_checkpoint_serving_elastic_no_cycles(self, tmp_path):
        from paddle_tpu.framework.checkpoint import CheckpointManager
        from paddle_tpu.serving import GenerationServer
        from paddle_tpu.distributed.elastic import ElasticManager
        import tests.test_observability as tob

        with alocks.instrument(long_hold_s=30.0) as aud:
            # async checkpoint: concurrent writer + reader
            mgr = CheckpointManager(str(tmp_path), keep_n=2,
                                    async_save=True)
            stop = threading.Event()

            def reader():
                while not stop.is_set():
                    mgr.latest()
                    time.sleep(0.001)

            rt = threading.Thread(target=reader)
            rt.start()
            for step in range(4):
                mgr.save({"w": np.arange(8, dtype=np.float32)}, step)
            mgr.wait()
            stop.set()
            rt.join()
            assert mgr.restore() is not None
            mgr.close()

            # serving: submit/drain under load
            srv = GenerationServer(tob.FakeEngine(slots=2))
            reqs = [srv.submit([1, 2, 3], max_new_tokens=4)
                    for _ in range(5)]
            assert srv.shutdown(drain=True, timeout=30)
            for r in reqs:
                assert r["done"].is_set()

            # elastic: heartbeat + watch threads over a fake store
            em = ElasticManager(_MemStore(), "0", ttl=0.5, interval=0.05,
                                stability_ticks=1)
            em.start()
            time.sleep(0.3)
            em._watch_tick()   # user-driven tick racing the thread
            em.stop()

        cycles = aud.cycles()
        assert not cycles, f"lock-order cycles: {cycles}"
        assert not [d for d in aud.diagnostics() if d.rule == "PTK001"]
        # the named subsystem locks actually went through the shim
        names = set(aud.acquisitions)
        assert any(n.startswith("checkpoint.manager") for n in names)
        assert any(n.startswith("serving.submit") for n in names)
        assert any(n.startswith("elastic.watch_tick") for n in names)


# ---------------------------------------------------------------------------
# flush-site metrics (satellite: stack-origin attribution)
# ---------------------------------------------------------------------------

class TestFlushSiteMetrics:
    def test_flag_populates_site_labeled_counter(self):
        from paddle_tpu.core import fusion
        fusion._M_flush_sites.reset()
        set_flags({"FLAGS_fusion_flush_origin": 1})
        try:
            x = paddle.to_tensor(np.ones((4,), np.float32))
            y = paddle.add(paddle.multiply(x, 2.0), 1.0)
            y.numpy()
        finally:
            set_flags({"FLAGS_fusion_flush_origin": 0})
        series = fusion._M_flush_sites.series()
        labeled = [dict(k) for k in series if k]
        assert any("test_analysis.py" in c.get("site", "")
                   and c.get("reason") == "host_read" for c in labeled), \
            series

    def test_flag_off_is_free(self):
        from paddle_tpu.core import fusion
        fusion._M_flush_sites.reset()
        x = paddle.to_tensor(np.ones((4,), np.float32))
        y = paddle.add(paddle.multiply(x, 2.0), 1.0)
        y.numpy()
        assert not [k for k in fusion._M_flush_sites.series() if k]

    def test_site_cardinality_cap_collapses_to_other(self):
        """ISSUE 7 satellite: a long-lived process must not grow one
        counter cell per distinct call site forever — past the cap new
        sites land in '<other>', so planner attribution can't blow up
        metric cardinality. Known sites keep their own label."""
        from paddle_tpu.core import fusion
        fusion._M_flush_sites.reset()
        saved = set(fusion._seen_flush_sites)
        try:
            fusion._seen_flush_sites.clear()
            fusion._seen_flush_sites.update(
                f"fake/site_{i}.py:1" for i in range(
                    fusion._MAX_FLUSH_SITES))
            set_flags({"FLAGS_fusion_flush_origin": 1})
            try:
                x = paddle.to_tensor(np.ones((4,), np.float32))
                y = paddle.add(paddle.multiply(x, 2.0), 1.0)
                y.numpy()
            finally:
                set_flags({"FLAGS_fusion_flush_origin": 0})
            labels = {dict(k).get("site")
                      for k in fusion._M_flush_sites.series() if k}
            assert "<other>" in labels, labels
            assert not any(l and "test_analysis.py" in l
                           for l in labels), labels
            # the set itself must not have grown past the cap
            assert len(fusion._seen_flush_sites) <= \
                fusion._MAX_FLUSH_SITES
        finally:
            fusion._seen_flush_sites.clear()
            fusion._seen_flush_sites.update(saved)
            fusion._M_flush_sites.reset()


# ---------------------------------------------------------------------------
# report surface + self-check
# ---------------------------------------------------------------------------

class TestReportSurface:
    def test_report_composes_capture_and_lint(self):
        def step():
            x = paddle.to_tensor(np.ones((4,), np.float32))
            paddle.add(x, 1.0).numpy()

        rep = report(step, warmup=1)
        assert rep.capture is not None and rep.capture.flushes
        assert rep.lint is not None and rep.lint.files_scanned > 100
        text = rep.render()
        assert "capture report" in text and "lint:" in text
        d = rep.to_dict()
        assert "capture" in d and "lint" in d

    def test_self_check_passes(self):
        out = self_check()
        assert out["ok"], out

    def test_cli_rules_and_main(self, capsys):
        from paddle_tpu.analysis.__main__ import main
        assert main(["--rules"]) == 0
        out = capsys.readouterr().out
        for rid in RULES:
            assert rid in out

    def test_analysis_metrics_registered(self):
        from paddle_tpu.observability import metrics as om
        snap = om.snapshot()
        assert "analysis" in snap
        assert snap["analysis"].get("audits_total", 0) >= 1
