"""Generation serving: the compiled fixed-slot decode engine and the
continuous-batching server (VERDICT r4 #4: "serving == generation").
Oracle = LlamaForCausalLM.generate (the parity KV-cache path); the
engine's static-cache decode must produce the same greedy tokens.
ref role: analysis_predictor.h + fused_multi_transformer_op.cu."""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import GenerationServer, LlamaDecodeEngine

CFG = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=2, use_flash_attention=False)


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    return LlamaForCausalLM(LlamaConfig.tiny(**CFG))


def _oracle(model, prompt, n_new):
    ids = paddle.to_tensor(np.asarray(prompt, np.int32)[None, :])
    full = model.generate(ids, max_new_tokens=n_new)
    return list(np.asarray(full.numpy())[0, len(prompt):])


class TestDecodeEngine:
    def test_single_request_matches_generate_oracle(self, model):
        eng = LlamaDecodeEngine(model, max_slots=2, max_seq=64)
        prompt = [5, 9, 11, 3]
        got = eng.generate(prompt, max_new_tokens=8)
        assert got == _oracle(model, prompt, 8)

    def test_slots_are_independent(self, model):
        """Two interleaved requests in different slots produce exactly
        their single-request sequences (no cache cross-talk)."""
        eng = LlamaDecodeEngine(model, max_slots=2, max_seq=64)
        p0, p1 = [1, 2, 3], [40, 41, 42, 43, 44]
        o0 = [eng.prefill(0, p0)]
        o1 = [eng.prefill(1, p1)]
        for _ in range(5):
            nxt = eng.step()
            o0.append(int(nxt[0]))
            o1.append(int(nxt[1]))
        assert o0 == _oracle(model, p0, 6)
        assert o1 == _oracle(model, p1, 6)

    def test_slot_reuse_after_release(self, model):
        eng = LlamaDecodeEngine(model, max_slots=1, max_seq=64)
        a = eng.generate([7, 8], max_new_tokens=4)
        b = eng.generate([7, 8], max_new_tokens=4)
        assert a == b  # stale cache rows must not leak into reuse

    def test_int8_engine_decodes(self, model):
        """int8 path: real s8 matmuls end-to-end; tokens are valid and
        deterministic, and the first-step logits stay close to fp."""
        eng8 = LlamaDecodeEngine(model, max_slots=1, max_seq=64,
                                 int8=True)
        out = eng8.generate([5, 9, 11], max_new_tokens=6)
        assert len(out) == 6
        assert all(0 <= t < CFG["vocab_size"] for t in out)
        assert out == eng8.generate([5, 9, 11], max_new_tokens=6)

    def test_export_decode_roundtrip(self, model):
        """AOT export: the serialized decode step runs without the
        engine class and matches the live step (ref: the predictor's
        self-contained analyzed program)."""
        import jax
        import jax.numpy as jnp

        eng = LlamaDecodeEngine(model, max_slots=2, max_seq=32)
        eng.prefill(0, [3, 4, 5])
        blob = eng.export_decode()
        assert isinstance(blob, (bytes, bytearray)) and len(blob) > 0
        rebuilt = jax.export.deserialize(bytearray(blob))
        args = (eng.params, eng.k_cache, eng.v_cache,
                jnp.asarray(eng.last_ids), jnp.asarray(eng.pos))
        nxt_aot, _, _ = rebuilt.call(*args)
        nxt_live, _, _ = jax.jit(eng._decode_impl)(*args)
        assert int(nxt_aot[0]) == int(nxt_live[0])


class TestContinuousBatching:
    def test_concurrent_requests_share_steps(self, model):
        """Three concurrent requests over two slots: every result
        matches its oracle, and the shared decode loop runs FEWER
        steps than serial execution would (iteration-level batching)."""
        eng = LlamaDecodeEngine(model, max_slots=2, max_seq=64)
        srv = GenerationServer(eng)
        jobs = [([1, 2, 3], 8), ([40, 41], 5), ([7, 9, 2, 4], 6)]
        results = {}

        def run(i, prompt, n):
            results[i] = srv.generate(prompt, n, timeout=120)

        ts = [threading.Thread(target=run, args=(i, p, n))
              for i, (p, n) in enumerate(jobs)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
        for i, (p, n) in enumerate(jobs):
            assert results[i] == _oracle(model, p, n), i
        assert srv.admitted == 3
        # serial would need sum(n-1) = 7+4+5 = 16 decode steps; two
        # slots sharing iterations must do with fewer
        assert srv.steps_run < 16, srv.steps_run

    def test_late_request_joins_running_batch(self, model):
        """A request submitted mid-flight is admitted at a step
        boundary and still matches its oracle."""
        eng = LlamaDecodeEngine(model, max_slots=2, max_seq=64)
        srv = GenerationServer(eng)
        first = srv.submit([1, 2, 3], 12)
        # wait until the loop is actually decoding, then join
        import time
        for _ in range(200):
            if srv.steps_run >= 2:
                break
            time.sleep(0.05)
        second = srv.generate([50, 51, 52], 4, timeout=120)
        assert first["done"].wait(120)
        assert list(first["out"]) == _oracle(model, [1, 2, 3], 12)
        assert second == _oracle(model, [50, 51, 52], 4)

    def test_eos_stops_generation(self, model):
        # find the greedy first token for the prompt and use it as eos
        eos = _oracle(model, [5, 9, 11, 3], 1)[0]
        eng = LlamaDecodeEngine(model, max_slots=1, max_seq=64,
                                eos_id=int(eos))
        srv = GenerationServer(eng)
        out = srv.generate([5, 9, 11, 3], 10, timeout=120)
        assert out == [eos]


class TestServeGenerateEndpoint:
    def test_http_generate_concurrent(self, model, tmp_path):
        """The HTTP surface: save the artifact, serve(generate=True),
        POST /generate concurrently, outputs match the oracle."""
        import io
        import urllib.request

        from paddle_tpu.inference import save_inference_model, serve

        path = str(tmp_path / "llama_srv")
        save_inference_model(path, model)
        server = serve(path, port=0, block=False, generate=True,
                       max_slots=2, max_seq=64)
        try:
            port = server.server_address[1]
            url = f"http://127.0.0.1:{port}/generate"

            def post(prompt, n):
                buf = io.BytesIO()
                np.savez(buf, input_ids=np.asarray(prompt, np.int32),
                         max_new_tokens=np.int32(n))
                req = urllib.request.Request(
                    url, data=buf.getvalue(), method="POST")
                with urllib.request.urlopen(req, timeout=120) as r:
                    out = np.load(io.BytesIO(r.read()))
                return list(out["output_ids"])

            jobs = [([1, 2, 3], 6), ([9, 8], 4)]
            results = {}

            def run(i, p, n):
                results[i] = post(p, n)

            ts = [threading.Thread(target=run, args=(i, p, n))
                  for i, (p, n) in enumerate(jobs)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=180)
            for i, (p, n) in enumerate(jobs):
                assert results[i] == _oracle(model, p, n), i
        finally:
            server.shutdown()


class TestServingErrorPaths:
    def test_overlong_prompt_fails_loudly(self, model):
        eng = LlamaDecodeEngine(model, max_slots=1, max_seq=16)
        srv = GenerationServer(eng)
        with pytest.raises(ValueError, match="prompt length"):
            srv.generate(list(range(40)), 4, timeout=60)
        # the loop survives: a valid request still serves
        out = srv.generate([1, 2, 3], 2, timeout=60)
        assert out == _oracle(model, [1, 2, 3], 2)

    def test_decode_steps_guards(self, model):
        eng = LlamaDecodeEngine(model, max_slots=2, max_seq=32)
        with pytest.raises(ValueError, match="EVERY slot"):
            eng.decode_steps(2)          # no slot active
        eng.prefill(0, [1, 2, 3])
        eng.prefill(1, [4, 5])
        with pytest.raises(ValueError, match="cache"):
            eng.decode_steps(64)         # would run past max_seq

    def test_submit_rejects_nonpositive_budget(self, model):
        eng = LlamaDecodeEngine(model, max_slots=1, max_seq=32)
        srv = GenerationServer(eng)
        with pytest.raises(ValueError, match="max_new_tokens"):
            srv.submit([1, 2], 0)


class TestDeadlinesAndDrain:
    """ISSUE 2: per-request deadlines + graceful drain-on-shutdown."""

    def test_shutdown_drains_in_flight(self, model):
        """Requests in flight (and already queued) when shutdown starts
        run to completion with their full oracle token streams — no
        completed token is dropped; new submissions are rejected."""
        eng = LlamaDecodeEngine(model, max_slots=2, max_seq=64)
        srv = GenerationServer(eng)
        reqs = [srv.submit([1, 2, 3], 10), srv.submit([40, 41], 8),
                srv.submit([7, 9, 2], 6)]  # 3rd waits queued
        import time
        for _ in range(200):
            if srv.steps_run >= 1:
                break
            time.sleep(0.05)
        assert srv.shutdown(drain=True, timeout=180)
        for req, (p, n) in zip(reqs, [([1, 2, 3], 10), ([40, 41], 8),
                                      ([7, 9, 2], 6)]):
            assert req["done"].is_set()
            assert req["error"] is None, req["error"]
            assert list(req["out"]) == _oracle(model, p, n)
        with pytest.raises(RuntimeError, match="shutting down"):
            srv.submit([5], 2)
        assert srv.stats()["rejected"] == 1
        assert srv.stats()["drained"] == 1

    def test_shutdown_no_drain_cancels_queued(self, model):
        import time
        eng = LlamaDecodeEngine(model, max_slots=1, max_seq=64)
        orig_step = eng.step

        def slow_step():  # hold the slot long enough that the queue
            time.sleep(0.15)  # is still populated at shutdown time
            return orig_step()

        eng.step = slow_step
        srv = GenerationServer(eng)
        first = srv.submit([1, 2, 3], 8)
        queued = [srv.submit([4, 5], 8) for _ in range(3)]
        for _ in range(200):
            if srv.steps_run >= 1:
                break
            time.sleep(0.05)
        assert srv.shutdown(drain=False, timeout=180)
        # the active request still finished intact
        assert first["done"].is_set() and first["error"] is None
        assert list(first["out"]) == _oracle(model, [1, 2, 3], 8)
        # at least the tail of the queue was cancelled cleanly
        cancelled = [r for r in queued
                     if isinstance(r["error"], RuntimeError)]
        assert cancelled, [r["error"] for r in queued]
        for r in queued:
            assert r["done"].is_set()

    def test_queued_deadline_expires(self, model):
        """A request whose deadline passes while it waits in the queue
        fails with TimeoutError without consuming a slot."""
        import time
        eng = LlamaDecodeEngine(model, max_slots=1, max_seq=64)
        orig_step = eng.step

        def slow_step():  # hold the slot past the queued deadline on
            time.sleep(0.02)  # fast hosts too
            return orig_step()

        eng.step = slow_step
        srv = GenerationServer(eng)
        blocker = srv.submit([1, 2, 3], 30)      # hog the only slot
        starved = srv.submit([9, 8], 8, deadline=0.2)
        with pytest.raises(ValueError, match="deadline"):
            srv.submit([1, 2], 4, deadline=0.0)
        assert starved["done"].wait(60)
        assert isinstance(starved["error"], TimeoutError)
        assert blocker["done"].wait(120)
        assert blocker["error"] is None
        assert srv.stats()["deadline_expired"] >= 1
        srv.shutdown()

    def test_active_deadline_keeps_partial_tokens(self, model):
        """An active request that exceeds its deadline is failed at a
        step boundary but keeps the tokens it already produced."""
        import time
        eng = LlamaDecodeEngine(model, max_slots=1, max_seq=256)
        orig_step = eng.step

        def slow_step():  # pin step cost so the deadline bites on any
            time.sleep(0.05)  # host, fast or slow
            return orig_step()

        eng.step = slow_step
        srv = GenerationServer(eng)
        req = srv.submit(list(range(1, 6)), 200, deadline=0.75)
        assert req["done"].wait(120)
        assert isinstance(req["error"], TimeoutError)
        assert len(req["out"]) >= 1          # partial stream retained
        assert len(req["out"]) < 200
        # the slot was freed: a fresh request still serves
        out = srv.generate([1, 2, 3], 2, timeout=60)
        assert out == _oracle(model, [1, 2, 3], 2)
        srv.shutdown()
