"""Train-mode BatchNorm gradient regression test.

The backward must flow through the batch statistics (mean/var centering
terms) — treating them as constants gives evaluation-style gradients that
explode through deep pre-activation stacks (caught on DenseNet-121: grads
reached 1e24 at init). torch.nn.functional.batch_norm is the reference.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")


def test_train_mode_bn_matches_torch_fwd_bwd():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 3, 8, 8)).astype(np.float32)
    w = rng.normal(size=(3,)).astype(np.float32)
    b = rng.normal(size=(3,)).astype(np.float32)
    g_out = rng.normal(size=(4, 3, 8, 8)).astype(np.float32)

    xt = torch.tensor(x, requires_grad=True)
    wt = torch.tensor(w, requires_grad=True)
    bt = torch.tensor(b, requires_grad=True)
    rm, rv = torch.zeros(3), torch.ones(3)
    out_t = torch.nn.functional.batch_norm(xt, rm, rv, wt, bt,
                                           training=True, momentum=0.1)
    out_t.backward(torch.tensor(g_out))

    xp = paddle.to_tensor(x, stop_gradient=False)
    wp = paddle.to_tensor(w, stop_gradient=False)
    bp = paddle.to_tensor(b, stop_gradient=False)
    rmp = paddle.to_tensor(np.zeros(3, np.float32))
    rvp = paddle.to_tensor(np.ones(3, np.float32))
    # paddle momentum=0.9 == torch momentum=0.1 (decay vs update fraction)
    out_p = F.batch_norm(xp, rmp, rvp, wp, bp, training=True, momentum=0.9)
    paddle.autograd.backward([out_p], [paddle.to_tensor(g_out)])

    np.testing.assert_allclose(out_p.numpy(), out_t.detach().numpy(),
                               atol=1e-5)
    np.testing.assert_allclose(xp.grad.numpy(), xt.grad.numpy(), atol=1e-4)
    np.testing.assert_allclose(wp.grad.numpy(), wt.grad.numpy(), atol=1e-4)
    np.testing.assert_allclose(bp.grad.numpy(), bt.grad.numpy(), atol=1e-4)
    np.testing.assert_allclose(rmp.numpy(), rm.numpy(), atol=1e-5)
    np.testing.assert_allclose(rvp.numpy(), rv.numpy(), atol=1e-5)


def test_deep_preact_stack_grads_bounded():
    """20 pre-activation BN->ReLU->Conv layers: max grad must stay sane
    (the broken eval-style backward gave ~e^20 growth)."""
    import paddle_tpu.nn as nn

    layers = []
    ch = 8
    for _ in range(20):
        layers += [nn.BatchNorm2D(ch), nn.ReLU(),
                   nn.Conv2D(ch, ch, 3, padding=1, bias_attr=False)]
    m = nn.Sequential(*layers)
    m.train()
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(4, ch, 8, 8))
        .astype(np.float32))
    out = m(x)
    out.mean().backward()
    gm = max(float(np.abs(np.asarray(p.grad._data)).max())
             for p in m.parameters() if p.grad is not None)
    assert gm < 1e3, f"gradient explosion through BN stack: max|g|={gm:.3e}"


def test_bn_uncentered_input_variance_stable():
    """Training BN on data with |mean| >> sigma must still normalize
    correctly: the one-pass E[x^2]-m^2 variance cancels in f32 at
    mean ~3000 and trained on garbage (review regression)."""
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(0)
    x = (rng.standard_normal((8, 16, 4, 4)) + 3000.0).astype(np.float32)
    rm = paddle.to_tensor(np.zeros(16, np.float32))
    rv = paddle.to_tensor(np.ones(16, np.float32))
    w = paddle.to_tensor(np.ones(16, np.float32))
    b = paddle.to_tensor(np.zeros(16, np.float32))
    y = F.batch_norm(paddle.to_tensor(x), rm, rv, w, b,
                     training=True).numpy()
    ref = (x - x.mean(axis=(0, 2, 3), keepdims=True)) / \
        x.std(axis=(0, 2, 3), keepdims=True)
    assert np.abs(y - ref).max() < 2e-2
    # running var must be ~1, not garbage
    np.testing.assert_allclose(rv.numpy(), 1.0, atol=0.2)


def test_bn_uncentered_large_batch_sampled_repair():
    """Cold-anchor repair with a STRIDED sample (batch > 8 so the
    stride exceeds 1): hostile-mean data on the first training step
    must still normalize within the sampled estimator's tolerance."""
    rng = np.random.default_rng(1)
    x = (rng.standard_normal((64, 16, 4, 4)) * 2.0 + 5000.0) \
        .astype(np.float32)
    rm = paddle.to_tensor(np.zeros(16, np.float32))
    rv = paddle.to_tensor(np.ones(16, np.float32))
    w = paddle.to_tensor(np.ones(16, np.float32))
    b = paddle.to_tensor(np.zeros(16, np.float32))
    y = F.batch_norm(paddle.to_tensor(x), rm, rv, w, b,
                     training=True).numpy()
    ref = (x - x.mean(axis=(0, 2, 3), keepdims=True)) / \
        x.std(axis=(0, 2, 3), keepdims=True)
    # sampled variance (1/8 of rows, ~sqrt(2/128)=12% rel var error):
    # normalization must be statistically right, not exact — the
    # failure mode being excluded is the naive form's 50%+ garbage
    assert np.abs(y - ref).max() < 0.2 * np.abs(ref).max()
    # running var: momentum EMA 0.9*1 + 0.1*var(~4) = ~1.3
    np.testing.assert_allclose(rv.numpy(), 1.3, rtol=0.25)


def test_bn_warm_anchor_exact_one_pass():
    """Steady state: anchor (running mean) near the true mean -> the
    fast one-pass variance is used and matches the two-pass reference
    tightly even for means far from zero."""
    rng = np.random.default_rng(2)
    x = (rng.standard_normal((16, 8, 6, 6)) + 300.0).astype(np.float32)
    m_true = x.mean(axis=(0, 2, 3))
    rm = paddle.to_tensor((m_true + 0.5).astype(np.float32))  # warm
    rv = paddle.to_tensor(np.ones(8, np.float32))
    w = paddle.to_tensor(np.ones(8, np.float32))
    b = paddle.to_tensor(np.zeros(8, np.float32))
    y = F.batch_norm(paddle.to_tensor(x), rm, rv, w, b,
                     training=True).numpy()
    ref = (x - x.mean(axis=(0, 2, 3), keepdims=True)) / \
        x.std(axis=(0, 2, 3), keepdims=True)
    assert np.abs(y - ref).max() < 2e-3
