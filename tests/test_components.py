"""Tests for SP layers, auto-tuner, Engine, audio/text, custom ops."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestSequenceParallel:
    def test_column_row_roundtrip(self, rng):
        """Column->Row SP linear pair == plain two-layer matmul when run
        without a mesh (placement constraints are no-ops)."""
        from paddle_tpu.distributed.fleet.sequence_parallel_utils import (
            ColumnSequenceParallelLinear, RowSequenceParallelLinear,
            GatherOp, ScatterOp)
        col = ColumnSequenceParallelLinear(8, 16)
        row = RowSequenceParallelLinear(16, 8)
        x = paddle.to_tensor(rng.normal(size=(2, 4, 8)).astype(np.float32))
        out = row(col(x))
        assert out.shape == [2, 4, 8]
        # scatter/gather are identity without a mesh
        np.testing.assert_allclose(GatherOp.apply(x).numpy(), x.numpy())
        np.testing.assert_allclose(ScatterOp.apply(x).numpy(), x.numpy())


class TestSequenceParallelMeshed:
    def test_sp_linears_match_serial_under_mesh(self, rng):
        """With fleet mp active, the SP column/row pair inside jit must
        produce the same numbers as an unsharded matmul pair (the
        constraints change placement, never values)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet.sequence_parallel_utils import (
            ColumnSequenceParallelLinear, RowSequenceParallelLinear)

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                                   "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        try:
            paddle.seed(11)
            col = ColumnSequenceParallelLinear(8, 16)
            row = RowSequenceParallelLinear(16, 8)
            assert col.weight._dist_attr is not None  # mp-sharded

            x_np = rng.normal(size=(2, 8, 8)).astype(np.float32)

            def fwd(x_arr, cw, cb, rw, rb):
                old = [col.weight._data, col.bias._data,
                       row.weight._data, row.bias._data]
                try:
                    col.weight._data, col.bias._data = cw, cb
                    row.weight._data, row.bias._data = rw, rb
                    from paddle_tpu.core.tensor import Tensor
                    return row(col(Tensor(x_arr)))._data
                finally:
                    (col.weight._data, col.bias._data,
                     row.weight._data, row.bias._data) = old

            out = jax.jit(fwd)(
                jnp.asarray(x_np), col.weight._data, col.bias._data,
                row.weight._data, row.bias._data)
            # serial oracle with the same (gathered) weights
            ref = (x_np @ np.asarray(col.weight._data)
                   + np.asarray(col.bias._data))
            ref = ref @ np.asarray(row.weight._data) + np.asarray(
                row.bias._data)
            np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)
        finally:
            from paddle_tpu.distributed.fleet.fleet import _reset_for_tests
            _reset_for_tests()


class TestAutoTuner:
    def test_prune_rules(self):
        from paddle_tpu.distributed.auto_tuner import Prune, SearchSpace
        space = SearchSpace(num_devices=8, global_batch_size=8,
                            num_layers=24)
        prune = Prune(space)
        assert prune.keep({"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                           "sharding_degree": 1, "sharding_stage": 1,
                           "micro_batch_size": 1})
        # wrong device product
        assert not prune.keep({"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 2, "sharding_degree": 2,
                               "sharding_stage": 1, "micro_batch_size": 1})
        # layers not divisible by pp
        space2 = SearchSpace(num_devices=8, num_layers=10)
        assert not Prune(space2).keep(
            {"dp_degree": 1, "mp_degree": 2, "pp_degree": 4,
             "sharding_degree": 1, "sharding_stage": 1,
             "micro_batch_size": 1})

    def test_tune_selects_best_and_survives_failures(self):
        from paddle_tpu.distributed.auto_tuner import (AutoTuner,
                                                       SearchSpace)
        space = SearchSpace(num_devices=4, dp_degree=(1, 2, 4),
                            mp_degree=(1, 2, 4), pp_degree=(1,),
                            sharding_degree=(1,), sharding_stage=(1,),
                            micro_batch_size=(1,), global_batch_size=4,
                            num_layers=4)

        def trial(cfg):
            if cfg["mp_degree"] == 4:
                raise MemoryError("oom")
            return 100.0 * cfg["dp_degree"]  # dp=4 wins

        tuner = AutoTuner(space, trial)
        best = tuner.tune()
        assert best["config"]["dp_degree"] == 4
        errors = [h for h in tuner.recorder.history if h["metric"] is None]
        assert errors and "MemoryError" in errors[0]["error"]


class TestEngine:
    def test_fit_evaluate_decreasing_loss(self, rng):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.auto_parallel import Engine

        model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(),
                              nn.Linear(16, 1))
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=model.parameters())
        X = rng.normal(size=(64, 4)).astype(np.float32)
        y = (X @ np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32))

        def loss_fn(out, label):
            d = out - label
            return (d * d).mean()

        eng = Engine(model=model, loss=loss_fn, optimizer=opt)
        data = [(X[i:i + 16], y[i:i + 16]) for i in range(0, 64, 16)]
        hist = eng.fit(data, epochs=10)
        assert hist["loss"][-1] < hist["loss"][0] * 0.5
        ev = eng.evaluate(data)
        assert ev["loss"] is not None and ev["loss"] < hist["loss"][0]

    def test_auto_recompute_picks_repeated_blocks(self, rng):
        """strategy.recompute.enable wraps the largest repeated-block
        family (the reference's auto segment picking,
        passes/auto_parallel_recompute.py) and numerics match the
        unwrapped model."""
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.auto_parallel import Engine, Strategy

        class Block(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, x):
                return paddle.nn.functional.relu(self.fc(x))

        def make():
            paddle.seed(7)
            m = nn.Sequential(Block(), Block(), Block(), nn.Linear(8, 1))
            opt = paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=m.parameters())
            return m, opt

        X = rng.normal(size=(16, 8)).astype(np.float32)
        y = rng.normal(size=(16, 1)).astype(np.float32)
        loss_fn = lambda o, l: ((o - l) ** 2).mean()  # noqa: E731

        m1, o1 = make()
        eng = Engine(model=m1, loss=loss_fn, optimizer=o1,
                     strategy=Strategy(recompute={"enable": True}))
        h1 = eng.fit([(X, y)], epochs=3)
        # the three Blocks (largest repeated family) got wrapped; the
        # lone tail Linear did not
        assert all(getattr(b, "_recompute_wrapped", False)
                   for b in [m1[0], m1[1], m1[2]])
        assert not getattr(m1[3], "_recompute_wrapped", False)

        m2, o2 = make()
        eng2 = Engine(model=m2, loss=loss_fn, optimizer=o2)
        h2 = eng2.fit([(X, y)], epochs=3)
        np.testing.assert_allclose(h1["loss"], h2["loss"], rtol=2e-4)

    def test_save_load_roundtrip(self, rng, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.auto_parallel import Engine

        def make():
            paddle.seed(3)
            m = nn.Linear(4, 2)
            opt = paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=m.parameters())
            return m, Engine(model=m, loss=lambda o, l: ((o - l) ** 2).mean(),
                             optimizer=opt)

        m1, e1 = make()
        data = [(rng.normal(size=(8, 4)).astype(np.float32),
                 rng.normal(size=(8, 2)).astype(np.float32))]
        e1.fit(data, epochs=2)
        e1.save(str(tmp_path))
        m2, e2 = make()
        e2.load(str(tmp_path))
        np.testing.assert_allclose(m2.weight.numpy(), m1.weight.numpy())


class TestAudio:
    def test_mel_matrix_shape_and_norm(self):
        fb = paddle.audio.compute_fbank_matrix(16000, 512, n_mels=40)
        assert fb.shape == [40, 257]
        assert float(fb.numpy().sum()) > 0

    def test_log_mel_spectrogram(self, rng):
        x = paddle.to_tensor(rng.normal(size=(2, 2048)).astype(np.float32))
        feat = paddle.audio.LogMelSpectrogram(sr=16000, n_fft=256,
                                              n_mels=32)(x)
        assert feat.shape[0] == 2 and feat.shape[1] == 32

    def test_mfcc(self, rng):
        x = paddle.to_tensor(rng.normal(size=(1, 2048)).astype(np.float32))
        feat = paddle.audio.MFCC(sr=16000, n_mfcc=13, n_fft=256,
                                 n_mels=32)(x)
        assert feat.shape[1] == 13


class TestText:
    def test_viterbi_matches_bruteforce(self, rng):
        import itertools
        from paddle_tpu.text import ViterbiDecoder
        N, T = 3, 4
        pot = rng.normal(size=(1, T, N)).astype(np.float32)
        trans = rng.normal(size=(N, N)).astype(np.float32)
        dec = ViterbiDecoder(paddle.to_tensor(trans),
                             include_bos_eos_tag=False)
        scores, paths = dec(paddle.to_tensor(pot))
        # brute force over all tag sequences
        best_s, best_p = -1e9, None
        for seq in itertools.product(range(N), repeat=T):
            s = pot[0, 0, seq[0]] + sum(
                trans[seq[t - 1], seq[t]] + pot[0, t, seq[t]]
                for t in range(1, T))
            if s > best_s:
                best_s, best_p = s, seq
        np.testing.assert_allclose(float(scores.numpy()[0]), best_s,
                                   rtol=1e-5)
        np.testing.assert_array_equal(paths.numpy()[0], best_p)


class TestTextLengths:
    def test_viterbi_respects_lengths(self, rng):
        """Padded timesteps must not affect scores/paths."""
        from paddle_tpu.text import viterbi_decode
        N = 3
        pot_short = rng.normal(size=(1, 3, N)).astype(np.float32)
        trans = rng.normal(size=(N, N)).astype(np.float32)
        s_ref, p_ref = viterbi_decode(
            paddle.to_tensor(pot_short), paddle.to_tensor(trans),
            include_bos_eos_tag=False)
        # pad with huge emissions that would hijack an unmasked decode
        pad = np.full((1, 2, N), 50.0, np.float32)
        pot_padded = np.concatenate([pot_short, pad], axis=1)
        s, p = viterbi_decode(
            paddle.to_tensor(pot_padded), paddle.to_tensor(trans),
            lengths=paddle.to_tensor(np.array([3], np.int32)),
            include_bos_eos_tag=False)
        np.testing.assert_allclose(float(s.numpy()[0]),
                                   float(s_ref.numpy()[0]), rtol=1e-5)
        np.testing.assert_array_equal(p.numpy()[0, :3], p_ref.numpy()[0])
        assert (p.numpy()[0, 3:] == 0).all()


class TestAudioTopDb:
    def test_top_db_clips(self, rng):
        x = paddle.to_tensor(rng.normal(size=(1, 2048)).astype(np.float32))
        clipped = paddle.audio.LogMelSpectrogram(
            sr=16000, n_fft=256, n_mels=32, top_db=10.0)(x).numpy()
        assert clipped.max() - clipped.min() <= 10.0 + 1e-4


class TestEngineModePreserved:
    def test_predict_keeps_eval_mode(self, rng):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.auto_parallel import Engine
        m = nn.Sequential(nn.Linear(4, 2), nn.Dropout(0.5))
        eng = Engine(model=m, loss=lambda o, l: ((o - l) ** 2).mean(),
                     optimizer=None)
        m.eval()
        eng.predict([rng.normal(size=(2, 4)).astype(np.float32)])
        assert not m.training  # was eval before, stays eval


class TestWatchdog:
    def test_passthrough_and_timeout(self, tmp_path):
        import time
        from paddle_tpu.distributed import Watchdog, WatchdogTimeout
        wd = Watchdog(timeout=5.0)
        assert wd.run(lambda: 42) == 42
        # errors propagate
        import pytest as _pytest
        with _pytest.raises(ZeroDivisionError):
            wd.run(lambda: 1 / 0)
        # hang detection + trace dump + abort callback
        aborted = []
        trace = str(tmp_path / "hang_trace.json")
        wd2 = Watchdog(timeout=0.2, on_timeout=lambda: aborted.append(1),
                       trace_path=trace)
        from paddle_tpu._native import lib
        if lib is not None:
            lib.tracer_start()
        with _pytest.raises(WatchdogTimeout):
            wd2.run(lambda: time.sleep(3))
        if lib is not None:
            lib.tracer_stop()
        assert aborted == [1]
        import os
        if lib is not None:
            assert os.path.exists(trace)

    def test_watched_train_step(self, rng):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed import Watchdog
        m = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        wd = Watchdog(timeout=60.0)
        x = paddle.to_tensor(rng.normal(size=(4, 4)).astype(np.float32))

        def step():
            loss = (m(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return float(loss)

        l0 = wd.run(step)
        l1 = wd.run(step)
        assert l1 < l0


class TestMultiPrecision:
    def test_bf16_moments_halve_state_and_track_fp32(self, rng):
        """multi_precision=False stores Adam moments in the param dtype;
        short-horizon training must stay close to the fp32-moment run."""
        import jax.numpy as jnp
        w_np = rng.normal(size=(8, 8))
        x_np = rng.normal(size=(4, 8)).astype(np.float32)

        def run(mp):
            w = paddle.Parameter(jnp.asarray(w_np, jnp.bfloat16))
            opt = paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=[w],
                                        multi_precision=mp)
            x = paddle.to_tensor(x_np)
            losses = []
            for _ in range(10):
                out = paddle.matmul(x, w.astype("float32"))
                loss = (out * out).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            state = opt._states[id(w)]
            return losses, state["moment1"].dtype

        l32, d32 = run(True)
        l16, d16 = run(False)
        assert str(d32) == "float32" and str(d16) == "bfloat16"
        assert l16[-1] < l16[0]  # still trains
        np.testing.assert_allclose(l16, l32, rtol=0.05)

    def test_adamw_forwards_multi_precision(self):
        """Regression: AdamW dropped the flag on the way to Adam."""
        import jax.numpy as jnp
        w = paddle.Parameter(jnp.zeros((2, 2), jnp.bfloat16))
        opt = paddle.optimizer.AdamW(parameters=[w],
                                     multi_precision=False)
        state = opt._init_state(w)
        assert str(state["moment1"].dtype) == "bfloat16"


class TestCustomOp:
    def test_register_and_autograd(self, rng):
        import jax.numpy as jnp
        from paddle_tpu.utils import register_op

        register_op("swish_test", lambda x: x * jnp.tanh(x),
                    override=True)
        import paddle_tpu.ops as ops
        x = paddle.to_tensor(rng.normal(size=(8,)).astype(np.float32),
                             stop_gradient=False)
        y = ops.swish_test(x)
        y.sum().backward()
        # d(x tanh x)/dx = tanh x + x sech^2 x
        xn = x.numpy()
        expect = np.tanh(xn) + xn * (1 - np.tanh(xn) ** 2)
        np.testing.assert_allclose(x.grad.numpy(), expect, atol=1e-5)

    def test_custom_vjp(self, rng):
        import jax.numpy as jnp
        from paddle_tpu.utils import register_op

        # identity fwd, doubled gradient in custom vjp: proves the vjp
        # override is what backward uses
        register_op("double_grad_test", lambda x: x,
                    vjp=lambda saved, g: (2.0 * g,), override=True)
        import paddle_tpu.ops as ops
        x = paddle.to_tensor(np.ones(4, np.float32), stop_gradient=False)
        ops.double_grad_test(x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), 2 * np.ones(4),
                                   atol=1e-6)

    def test_duplicate_registration_raises(self):
        from paddle_tpu.utils import register_op
        register_op("dup_test_op", lambda x: x, override=True)
        with pytest.raises(ValueError, match="already exists"):
            register_op("dup_test_op", lambda x: x)

    def test_cannot_shadow_builtin_op(self):
        from paddle_tpu.utils import register_op
        with pytest.raises(ValueError, match="already exists"):
            register_op("matmul", lambda x, y: x)

    def test_vjp_op_rejects_kwargs(self):
        from paddle_tpu.utils import register_op
        op = register_op("vjp_kwargs_test", lambda x: x,
                         vjp=lambda saved, g: (g,), override=True)
        x = paddle.to_tensor(np.ones(2, np.float32))
        with pytest.raises(ValueError, match="positional"):
            op(x, factor=2.0)
