"""Fused optimizer-step plane (optimizer/fused_step.py).

- fused-vs-eager trajectory equivalence per optimizer and per clip
  strategy (the kill switch FLAGS_fused_optimizer=0 is the reference)
- LR-schedule cache stability: <= 1 compile across 50 steps of a
  changing lr (lr rides as a 0-d device argument, never a baked const)
- checkpoint round-trips: train k steps, CheckpointManager.restore(),
  continue — the trajectory is BIT-identical to an uninterrupted run
  under both flag settings; optimizer state_dict() round-trips unchanged
- buffer donation (old param buffers are invalidated in the jitted
  steady state), fallback gates, AMP masked-step semantics
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.checkpoint import CheckpointManager
from paddle_tpu.observability import metrics as om
from paddle_tpu.optimizer import fused_step

opt_mod = paddle.optimizer


@pytest.fixture(autouse=True)
def _restore_flag():
    prev = paddle.get_flags("FLAGS_fused_optimizer")
    yield
    paddle.set_flags(prev)


def _make(n=3, shape=(4, 4), seed=0):
    rng = np.random.default_rng(seed)
    ps = [paddle.Parameter(rng.normal(size=shape).astype(np.float32))
          for _ in range(n)]
    gs = [rng.normal(size=shape).astype(np.float32) for _ in range(n)]
    return ps, gs


def _train(opt, ps, gs, steps, sched=None, start=0):
    for s in range(start, start + steps):
        for p, g in zip(ps, gs):
            p.grad = paddle.to_tensor(g * (1.0 + 0.1 * s))
        opt.step()
        if sched is not None:
            sched.step()
        opt.clear_grad()


def _run(optcls, fused, steps=5, clip=None, use_sched=True, **kw):
    paddle.set_flags({"FLAGS_fused_optimizer": 1 if fused else 0})
    ps, gs = _make()
    sched = None
    lr = kw.pop("learning_rate", 0.05)
    if use_sched:
        sched = opt_mod.lr.CosineAnnealingDecay(learning_rate=lr, T_max=10)
        lr = sched
    opt = optcls(learning_rate=lr, parameters=ps, grad_clip=clip, **kw)
    _train(opt, ps, gs, steps, sched)
    return [p.numpy().copy() for p in ps], opt.state_dict()


def _opt_counters():
    snap = om.snapshot().get("optimizer", {})
    return {k: snap.get(k, 0) for k in
            ("fused_steps_total", "fused_compiles_total",
             "cache_hits_total", "uncompiled_runs_total",
             "donated_bytes")}


OPTIMIZERS = [
    (opt_mod.SGD, {}),
    (opt_mod.SGD, {"weight_decay": 0.01}),
    (opt_mod.Momentum, {}),
    (opt_mod.Momentum, {"use_nesterov": True}),
    (opt_mod.Adagrad, {"learning_rate": 0.05}),
    (opt_mod.Adam, {}),
    (opt_mod.Adam, {"weight_decay": 0.01}),
    (opt_mod.Adam, {"multi_precision": False}),
    (opt_mod.AdamW, {}),
    (opt_mod.AdamW, {"apply_decay_param_fun": lambda n: "0" not in n}),
    (opt_mod.Adamax, {}),
    (opt_mod.RMSProp, {"learning_rate": 0.01}),
    (opt_mod.RMSProp, {"learning_rate": 0.01, "centered": True,
                       "momentum": 0.9}),
    (opt_mod.Lamb, {}),
    (opt_mod.Adadelta, {}),
    (opt_mod.ASGD, {"batch_num": 2}),
    (opt_mod.NAdam, {}),
    (opt_mod.RAdam, {}),
    (opt_mod.Rprop, {"use_sched": False}),
]


class TestEquivalence:
    @pytest.mark.parametrize(
        "optcls,kw", OPTIMIZERS,
        ids=[f"{c.__name__}-{i}" for i, (c, _) in enumerate(OPTIMIZERS)])
    def test_matches_eager_loop(self, optcls, kw):
        kw = dict(kw)
        use_sched = kw.pop("use_sched", True)
        fused, _ = _run(optcls, True, use_sched=use_sched, **kw)
        eager, _ = _run(optcls, False, use_sched=use_sched, **kw)
        for a, b in zip(fused, eager):
            # one executable reassociates f32 rounding vs per-op eager;
            # trajectories agree to f32 noise, not bit-for-bit
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6)

    def test_l1_regularizer_folds_into_program(self):
        from paddle_tpu.regularizer import L1Decay
        fused, _ = _run(opt_mod.Momentum, True, weight_decay=L1Decay(0.01))
        eager, _ = _run(opt_mod.Momentum, False,
                        weight_decay=L1Decay(0.01))
        for a, b in zip(fused, eager):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6)

    @pytest.mark.parametrize("clip", [
        paddle.nn.ClipGradByGlobalNorm(1.0),
        paddle.nn.ClipGradByNorm(0.5),
        paddle.nn.ClipGradByValue(0.3),
    ], ids=["global_norm", "norm", "value"])
    @pytest.mark.parametrize("optcls", [opt_mod.SGD, opt_mod.Adam])
    def test_clip_folded_into_program(self, optcls, clip):
        before = _opt_counters()
        fused, _ = _run(optcls, True, clip=clip)
        delta = _opt_counters()["fused_steps_total"] - \
            before["fused_steps_total"]
        assert delta == 5  # the clip fused, no fallback
        eager, _ = _run(optcls, False, clip=clip)
        for a, b in zip(fused, eager):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6)


class TestCachePolicy:
    def test_lr_schedule_cache_stability(self):
        """<= 1 compile across 50 steps of a changing-LR schedule: the
        per-step lr enters as a 0-d device-array argument, so a new lr
        value can never bust the program cache."""
        fused_step.clear_cache()
        before = _opt_counters()
        _run(opt_mod.Adam, True, steps=50)
        after = _opt_counters()
        compiles = after["fused_compiles_total"] - \
            before["fused_compiles_total"]
        hits = after["cache_hits_total"] - before["cache_hits_total"]
        uncompiled = after["uncompiled_runs_total"] - \
            before["uncompiled_runs_total"]
        assert compiles <= 1
        # step 1 runs un-jitted (first sighting), step 2 compiles,
        # steps 3..50 are pure cache hits: 100% steady-state hit rate
        assert uncompiled == 1
        assert hits == 48

    def test_shared_cache_across_instances(self):
        """A second optimizer with identical static config reuses the
        compiled program — zero extra compiles."""
        fused_step.clear_cache()
        _run(opt_mod.Adam, True, steps=4)
        before = _opt_counters()
        _run(opt_mod.Adam, True, steps=4)
        after = _opt_counters()
        assert after["fused_compiles_total"] == \
            before["fused_compiles_total"]
        assert after["cache_hits_total"] - before["cache_hits_total"] == 4

    def test_kill_switch_restores_eager_loop(self):
        before = _opt_counters()
        _run(opt_mod.Adam, False)
        after = _opt_counters()
        assert after["fused_steps_total"] == before["fused_steps_total"]

    def test_donation_invalidates_old_buffers(self):
        paddle.set_flags({"FLAGS_fused_optimizer": 1})
        ps, gs = _make()
        opt = opt_mod.Adam(learning_rate=0.01, parameters=ps)
        _train(opt, ps, gs, 2)  # step 1 eager sighting, step 2 compiles
        old = [p._data for p in ps]
        before = _opt_counters()["donated_bytes"]
        _train(opt, ps, gs, 1, start=2)
        assert _opt_counters()["donated_bytes"] > before
        # the donated input buffers are dead: the update happened in
        # place in device memory, not into a second copy of the model
        assert all(buf.is_deleted() for buf in old)

    @pytest.mark.parametrize("fused", [1, 0], ids=["fused", "eager"])
    def test_detached_snapshot_survives_donation(self, fused):
        """p.detach() taken between steps must stay readable, frozen at
        its point-in-time value, under BOTH flag settings (regression:
        the donating step deleted the shared buffer under the alias —
        README promises eager replace-don't-mutate parity)."""
        paddle.set_flags({"FLAGS_fused_optimizer": fused})
        ps, gs = _make()
        opt = opt_mod.Adam(learning_rate=0.01, parameters=ps)
        _train(opt, ps, gs, 2)  # warm past the second-sighting compile
        snaps = [p.detach() for p in ps]
        want = [s.numpy().copy() for s in snaps]
        _train(opt, ps, gs, 2, start=2)  # donating steady state
        for s, w, p in zip(snaps, want, ps):
            np.testing.assert_array_equal(s.numpy(), w)
            assert not np.array_equal(p.numpy(), w)  # params moved on

    def test_detached_grad_survives_scaler_unscale(self):
        """p.grad.detach() held across scaler.step() must survive the
        donated batched unscale / fused scaled step."""
        paddle.set_flags({"FLAGS_fused_optimizer": 1})
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        p = paddle.Parameter(np.ones(4, np.float32))
        opt = opt_mod.SGD(learning_rate=0.1, parameters=[p])
        for _ in range(3):  # warm the scaled program into its jit
            p.grad = paddle.to_tensor(np.full(4, 4.0, np.float32))
            scaler.step(opt)
            scaler.update()
            opt.clear_grad()
        p.grad = paddle.to_tensor(np.full(4, 4.0, np.float32))
        held = p.grad.detach()
        scaler.step(opt)
        scaler.update()
        np.testing.assert_array_equal(
            held.numpy(), np.full(4, 4.0, np.float32))  # still scaled

    def test_alias_registry_stays_bounded(self):
        """Transient detach() per step (grad logging) must not leak one
        registry entry per call — dead outer entries are swept on
        registration once the dict passes its bound."""
        from paddle_tpu.core import tensor as tensor_mod
        for _ in range(300):
            paddle.to_tensor(np.ones(2, np.float32)).detach()
        assert len(tensor_mod._buffer_aliases) <= 66

    def test_clip_subclass_still_clips_via_fallback(self):
        """A subclass of an in-tree clip falls back (it may override
        __call__), and the inherited eager __call__ still CLIPS —
        regression for the spec refactor silently no-op'ing subclasses."""
        class MyClip(paddle.nn.ClipGradByGlobalNorm):
            pass

        paddle.set_flags({"FLAGS_fused_optimizer": 1})
        ps, gs = _make()
        opt = opt_mod.SGD(learning_rate=0.1, parameters=ps,
                          grad_clip=MyClip(1e-3))
        before = _opt_counters()["fused_steps_total"]
        _train(opt, ps, gs, 1)
        assert _opt_counters()["fused_steps_total"] == before  # fell back
        ref, _ = _make()
        # with clip_norm 1e-3 the update is tiny: the clip applied
        for p, r in zip(ps, ref):
            assert np.abs(p.numpy() - r.numpy()).max() < 1e-3

    def test_fallback_on_unknown_clip(self):
        class OddClip:
            def __call__(self, params_grads):
                return params_grads

        paddle.set_flags({"FLAGS_fused_optimizer": 1})
        ps, gs = _make()
        opt = opt_mod.SGD(learning_rate=0.1, parameters=ps,
                          grad_clip=OddClip())
        before = om.snapshot().get("optimizer", {}).get(
            "fallbacks_total", 0)
        _train(opt, ps, gs, 2)
        after = om.snapshot().get("optimizer", {})["fallbacks_total"]
        assert (after if isinstance(after, (int, float))
                else sum(after.values())) > (
            before if isinstance(before, (int, float))
            else sum(before.values()))
        # and the eager fallback still trained
        assert not np.array_equal(ps[0].numpy(), _make()[0][0].numpy())


class TestCheckpointRoundTrip:
    @pytest.mark.parametrize("fused", [1, 0], ids=["fused", "eager"])
    def test_restore_continue_bit_identical(self, tmp_path, fused):
        """Train 3 steps, checkpoint, continue 3 more; a fresh
        model+optimizer restored from the checkpoint replays steps 4-6
        BIT-identically — state_dict carries everything (moments, beta
        powers, LR-scheduler state, global step)."""
        paddle.set_flags({"FLAGS_fused_optimizer": fused})

        def build():
            ps, gs = _make()
            sched = opt_mod.lr.CosineAnnealingDecay(
                learning_rate=0.05, T_max=10)
            opt = opt_mod.AdamW(learning_rate=sched, parameters=ps,
                                grad_clip=paddle.nn.ClipGradByGlobalNorm(
                                    1.0))
            return ps, gs, sched, opt

        # warm the fused program cache so every timed step below runs
        # the SAME jitted executable: the first sighting of a structure
        # runs un-jitted, whose f32 rounding differs bitwise from the
        # compiled program (steady state is what training loops live in)
        ps, gs, sched, opt = build()
        _train(opt, ps, gs, 2, sched)

        # uninterrupted reference: 6 straight steps
        ps, gs, sched, opt = build()
        _train(opt, ps, gs, 6, sched)
        want = [p.numpy().copy() for p in ps]
        want_sd = opt.state_dict()

        # interrupted run: 3 steps -> save -> restore -> 3 more
        ps, gs, sched, opt = build()
        _train(opt, ps, gs, 3, sched)
        cm = CheckpointManager(str(tmp_path))
        cm.save({"params": [paddle.to_tensor(p.numpy()) for p in ps],
                 "opt": opt.state_dict()}, step=3)
        del ps, opt, sched

        step, ckpt = cm.restore()
        assert step == 3
        ps2, gs, sched2, opt2 = build()
        for p, saved in zip(ps2, ckpt["params"]):
            p._data = saved._data.astype(p._data.dtype)
        opt2.set_state_dict(ckpt["opt"])
        _train(opt2, ps2, gs, 3, sched2, start=3)
        for got, ref in zip(ps2, want):
            assert got.numpy().tobytes() == ref.tobytes()
        got_sd = opt2.state_dict()
        assert set(got_sd) == set(want_sd)
        assert got_sd["global_step"] == want_sd["global_step"]

    @pytest.mark.parametrize("fused", [1, 0], ids=["fused", "eager"])
    def test_state_dict_round_trips_unchanged(self, fused):
        paddle.set_flags({"FLAGS_fused_optimizer": fused})
        ps, gs = _make()
        opt = opt_mod.Adam(learning_rate=0.01, parameters=ps)
        _train(opt, ps, gs, 4)
        sd = opt.state_dict()
        ps2, _ = _make()
        opt2 = opt_mod.Adam(learning_rate=0.01, parameters=ps2)
        opt2.set_state_dict(sd)
        sd2 = opt2.state_dict()
        assert set(sd) == set(sd2)
        for k, v in sd.items():
            if k == "global_step":
                assert sd2[k] == v
            else:
                assert sd2[k].numpy().tobytes() == v.numpy().tobytes()
                assert str(sd2[k].dtype) == str(v.dtype)


class TestStateDictVsDonation:
    @pytest.mark.parametrize("fused", [1, 0], ids=["fused", "eager"])
    def test_held_state_dict_survives_later_steps(self, fused, tmp_path):
        """state_dict() is a point-in-time snapshot: later (donating)
        steps must not invalidate it, and a restored checkpoint dict
        must stay readable after training resumes."""
        paddle.set_flags({"FLAGS_fused_optimizer": fused})
        ps, gs = _make()
        opt = opt_mod.Adam(learning_rate=0.01, parameters=ps)
        _train(opt, ps, gs, 3)
        sd = opt.state_dict()
        snap = {k: v.numpy().copy() for k, v in sd.items()
                if k != "global_step"}
        _train(opt, ps, gs, 2, start=3)  # donates the live leaves
        path = str(tmp_path / "opt.pdckpt")
        paddle.save(sd, path)  # serialize AFTER the extra steps
        loaded = paddle.load(path)
        for k, want in snap.items():
            np.testing.assert_array_equal(loaded[k].numpy(), want)
        # restored dict survives continued training too
        opt.set_state_dict(loaded)
        _train(opt, ps, gs, 2, start=5)
        for k, want in snap.items():
            np.testing.assert_array_equal(loaded[k].numpy(), want)


class TestAMPMaskedStep:
    @pytest.mark.parametrize("fused", [1, 0], ids=["fused", "eager"])
    def test_nonfinite_grad_keeps_params_and_state(self, fused):
        paddle.set_flags({"FLAGS_fused_optimizer": fused})
        ps, gs = _make()
        opt = opt_mod.Adam(learning_rate=0.05, parameters=ps)
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0,
                                       decr_every_n_nan_or_inf=1)
        _train(opt, ps, gs, 2)  # populate moments
        before_p = [p.numpy().copy() for p in ps]
        before_m = {k: v.numpy().copy()
                    for k, v in opt.state_dict().items()
                    if k != "global_step"}
        for p, g in zip(ps, gs):
            bad = g.copy()
            bad[0, 0] = np.inf
            p.grad = paddle.to_tensor(bad)
        scaler.step(opt)
        scaler.update()
        for p, want in zip(ps, before_p):
            np.testing.assert_array_equal(p.numpy(), want)
        for k, v in opt.state_dict().items():
            if k != "global_step":
                np.testing.assert_array_equal(v.numpy(), before_m[k])
        assert float(scaler.get_loss_scaling()) == 4.0

    @pytest.mark.parametrize("fused", [1, 0], ids=["fused", "eager"])
    def test_scaled_matches_plain_when_finite(self, fused):
        """GradScaler(scale)+step == plain step on finite grads."""
        paddle.set_flags({"FLAGS_fused_optimizer": fused})
        ps, gs = _make()
        opt = opt_mod.Adam(learning_rate=0.05, parameters=ps)
        scaler = paddle.amp.GradScaler(init_loss_scaling=256.0)
        for s in range(3):
            for p, g in zip(ps, gs):
                p.grad = paddle.to_tensor(
                    g * (1.0 + 0.1 * s) * 256.0)  # pre-scaled grads
            scaler.step(opt)
            scaler.update()
            opt.clear_grad()
        ps2, _ = _make()
        opt2 = opt_mod.Adam(learning_rate=0.05, parameters=ps2)
        _train(opt2, ps2, gs, 3)
        for a, b in zip(ps, ps2):
            np.testing.assert_allclose(a.numpy(), b.numpy(),
                                       rtol=2e-4, atol=1e-6)
