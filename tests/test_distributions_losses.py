"""Extended distribution zoo + loss tests against torch references
(ref: python/paddle/distribution/, nn/functional/loss.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distribution as D
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")
td = torch.distributions


def _close(a, b, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol,
                               atol=atol)


class TestDistributions:
    def test_gamma_log_prob_entropy_mean(self):
        a, r = np.array([2.0, 0.5], np.float32), np.array([1.5, 2.0],
                                                          np.float32)
        v = np.array([0.7, 1.3], np.float32)
        ours = D.Gamma(a, r)
        ref = td.Gamma(torch.tensor(a), torch.tensor(r))
        _close(ours.log_prob(paddle.to_tensor(v)).numpy(),
               ref.log_prob(torch.tensor(v)).numpy())
        _close(ours.entropy().numpy(), ref.entropy().numpy())
        _close(ours.mean.numpy(), ref.mean.numpy())
        _close(ours.variance.numpy(), ref.variance.numpy())

    def test_beta_log_prob_entropy(self):
        a, b = np.array([2.0, 3.0], np.float32), np.array([1.5, 0.7],
                                                          np.float32)
        v = np.array([0.3, 0.8], np.float32)
        ours = D.Beta(a, b)
        ref = td.Beta(torch.tensor(a), torch.tensor(b))
        _close(ours.log_prob(paddle.to_tensor(v)).numpy(),
               ref.log_prob(torch.tensor(v)).numpy())
        _close(ours.entropy().numpy(), ref.entropy().numpy())

    def test_dirichlet_log_prob_entropy(self):
        c = np.array([[2.0, 3.0, 0.5], [1.0, 1.0, 1.0]], np.float32)
        v = np.array([[0.2, 0.5, 0.3], [0.1, 0.6, 0.3]], np.float32)
        ours = D.Dirichlet(c)
        ref = td.Dirichlet(torch.tensor(c))
        _close(ours.log_prob(paddle.to_tensor(v)).numpy(),
               ref.log_prob(torch.tensor(v)).numpy())
        _close(ours.entropy().numpy(), ref.entropy().numpy())

    def test_poisson_binomial_geometric_log_prob(self):
        rate = np.array([2.0, 5.0], np.float32)
        k = np.array([1.0, 4.0], np.float32)
        _close(D.Poisson(rate).log_prob(paddle.to_tensor(k)).numpy(),
               td.Poisson(torch.tensor(rate)).log_prob(
                   torch.tensor(k)).numpy())
        n = np.array([10.0, 10.0], np.float32)
        p = np.array([0.3, 0.7], np.float32)
        _close(D.Binomial(n, p).log_prob(paddle.to_tensor(k)).numpy(),
               td.Binomial(torch.tensor(n), torch.tensor(p)).log_prob(
                   torch.tensor(k)).numpy())
        _close(D.Geometric(p).log_prob(paddle.to_tensor(k)).numpy(),
               td.Geometric(torch.tensor(p)).log_prob(
                   torch.tensor(k)).numpy())

    def test_studentt_cauchy_log_prob(self):
        df = np.array([3.0], np.float32)
        v = np.array([0.5], np.float32)
        _close(D.StudentT(df, 1.0, 2.0).log_prob(
                   paddle.to_tensor(v)).numpy(),
               td.StudentT(torch.tensor(df), 1.0, 2.0).log_prob(
                   torch.tensor(v)).numpy())
        _close(D.Cauchy(0.5, 1.5).log_prob(paddle.to_tensor(v)).numpy(),
               td.Cauchy(0.5, 1.5).log_prob(torch.tensor(v)).numpy())

    def test_mvn_log_prob_entropy(self):
        loc = np.array([1.0, -1.0], np.float32)
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
        v = np.array([0.3, 0.2], np.float32)
        ours = D.MultivariateNormal(loc, covariance_matrix=cov)
        ref = td.MultivariateNormal(torch.tensor(loc), torch.tensor(cov))
        _close(ours.log_prob(paddle.to_tensor(v)).numpy(),
               ref.log_prob(torch.tensor(v)).numpy())
        _close(ours.entropy().numpy(), ref.entropy().numpy())

    def test_sampling_statistics(self):
        paddle.seed(0)
        g = D.Gamma(np.float32(3.0), np.float32(2.0)).sample([20000])
        assert abs(float(g.numpy().mean()) - 1.5) < 0.05
        b = D.Beta(np.float32(2.0), np.float32(2.0)).sample([20000])
        assert abs(float(b.numpy().mean()) - 0.5) < 0.02
        p = D.Poisson(np.float32(4.0)).sample([20000])
        assert abs(float(p.numpy().mean()) - 4.0) < 0.1

    def test_gamma_rsample_differentiable(self):
        paddle.seed(0)
        a = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
        s = D.Gamma(a, np.float32(1.0)).rsample([256])
        s.mean().backward()
        assert a.grad is not None
        # E[d sample/d alpha] ≈ d mean/d alpha = 1/rate = 1
        assert 0.5 < float(a.grad.numpy()) < 1.5

    def test_independent_reinterprets_batch(self):
        base = D.Normal(np.zeros((3, 4), np.float32),
                        np.ones((3, 4), np.float32))
        ind = D.Independent(base, 1)
        assert ind.batch_shape == (3,)
        assert ind.event_shape == (4,)
        v = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
        lp = ind.log_prob(paddle.to_tensor(v))
        assert lp.shape == [3]
        ref = td.Independent(td.Normal(torch.zeros(3, 4),
                                       torch.ones(3, 4)), 1)
        _close(lp.numpy(), ref.log_prob(torch.tensor(v)).numpy())

    def test_transformed_distribution_lognormal(self):
        """Normal + ExpTransform == LogNormal."""
        tdist = D.TransformedDistribution(
            D.Normal(np.float32(0.3), np.float32(0.8)), D.ExpTransform())
        v = np.array([0.5, 2.0], np.float32)
        ref = td.LogNormal(torch.tensor(0.3), torch.tensor(0.8))
        _close(tdist.log_prob(paddle.to_tensor(v)).numpy(),
               ref.log_prob(torch.tensor(v)).numpy())

    def test_affine_sigmoid_transforms_roundtrip(self):
        x = np.array([-1.0, 0.5, 2.0], np.float32)
        for t in [D.AffineTransform(1.0, 2.0), D.SigmoidTransform(),
                  D.TanhTransform(), D.PowerTransform(2.0)]:
            xin = np.abs(x) + 0.1 if isinstance(
                t, D.PowerTransform) else x
            y = t.forward(paddle.to_tensor(xin))
            back = t.inverse(y)
            _close(back.numpy(), xin, rtol=1e-4)

    def test_kl_pairs(self):
        a = D.Gamma(np.float32(2.0), np.float32(1.5))
        b = D.Gamma(np.float32(3.0), np.float32(1.0))
        ra = td.Gamma(torch.tensor(2.0), torch.tensor(1.5))
        rb = td.Gamma(torch.tensor(3.0), torch.tensor(1.0))
        _close(D.kl_divergence(a, b).numpy(),
               td.kl_divergence(ra, rb).numpy())
        a2, b2 = D.Beta(2.0, 3.0), D.Beta(1.0, 1.0)
        ra2 = td.Beta(torch.tensor(2.0), torch.tensor(3.0))
        rb2 = td.Beta(torch.tensor(1.0), torch.tensor(1.0))
        _close(D.kl_divergence(a2, b2).numpy(),
               td.kl_divergence(ra2, rb2).numpy())


class TestNewLosses:
    def setup_method(self):
        rng = np.random.default_rng(0)
        self.x = rng.normal(size=(6, 5)).astype(np.float32)
        self.rng = rng

    def test_soft_margin_loss(self):
        y = np.sign(self.rng.normal(size=(6, 5))).astype(np.float32)
        ours = F.soft_margin_loss(paddle.to_tensor(self.x),
                                  paddle.to_tensor(y))
        ref = torch.nn.functional.soft_margin_loss(
            torch.tensor(self.x), torch.tensor(y))
        _close(ours.numpy(), ref.numpy())

    def test_multi_label_soft_margin(self):
        y = (self.rng.uniform(size=(6, 5)) > 0.5).astype(np.float32)
        ours = F.multi_label_soft_margin_loss(paddle.to_tensor(self.x),
                                              paddle.to_tensor(y))
        ref = torch.nn.functional.multilabel_soft_margin_loss(
            torch.tensor(self.x), torch.tensor(y))
        _close(ours.numpy(), ref.numpy())

    def test_multi_margin(self):
        lbl = self.rng.integers(0, 5, size=(6,)).astype(np.int64)
        ours = F.multi_margin_loss(paddle.to_tensor(self.x),
                                   paddle.to_tensor(lbl))
        ref = torch.nn.functional.multi_margin_loss(
            torch.tensor(self.x), torch.tensor(lbl))
        _close(ours.numpy(), ref.numpy())

    def test_poisson_nll(self):
        y = self.rng.poisson(2.0, size=(6, 5)).astype(np.float32)
        ours = F.poisson_nll_loss(paddle.to_tensor(self.x),
                                  paddle.to_tensor(y))
        ref = torch.nn.functional.poisson_nll_loss(
            torch.tensor(self.x), torch.tensor(y))
        _close(ours.numpy(), ref.numpy())
        ours_full = F.poisson_nll_loss(paddle.to_tensor(np.abs(self.x)),
                                       paddle.to_tensor(y),
                                       log_input=False, full=True)
        ref_full = torch.nn.functional.poisson_nll_loss(
            torch.tensor(np.abs(self.x)), torch.tensor(y),
            log_input=False, full=True)
        _close(ours_full.numpy(), ref_full.numpy())

    def test_gaussian_nll(self):
        y = self.rng.normal(size=(6, 5)).astype(np.float32)
        var = np.abs(self.rng.normal(size=(6, 5))).astype(np.float32) + 0.1
        ours = F.gaussian_nll_loss(paddle.to_tensor(self.x),
                                   paddle.to_tensor(y),
                                   paddle.to_tensor(var))
        ref = torch.nn.functional.gaussian_nll_loss(
            torch.tensor(self.x), torch.tensor(y), torch.tensor(var))
        _close(ours.numpy(), ref.numpy())

    def test_loss_layers_exist_and_reduce(self):
        import paddle_tpu.nn as nn
        y = np.sign(self.rng.normal(size=(6, 5))).astype(np.float32)
        for layer in [nn.SoftMarginLoss(reduction="sum"),
                      nn.SoftMarginLoss(reduction="none")]:
            out = layer(paddle.to_tensor(self.x), paddle.to_tensor(y))
            assert np.isfinite(out.numpy()).all()
        lbl = self.rng.integers(0, 5, size=(6,)).astype(np.int64)
        out = nn.MultiMarginLoss()(paddle.to_tensor(self.x),
                                   paddle.to_tensor(lbl))
        assert np.isfinite(float(out.item()))
