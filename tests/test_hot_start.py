"""Hot-start fleet (ISSUE 14): persistent executable cache, warm-bundle
boot pre-warm, and zero-downtime weight hot-swap.

Three planes pinned here:

- **Executable cache** (``FLAGS_executable_cache_dir``): compiled XLA
  artifacts persist on disk; a poisoned entry degrades to a counted
  miss + recompile, never a crash. The acceptance scenario runs TWO
  real processes against one cache dir + bundle: the second reaches
  its first captured train step and its first decode token with ZERO
  fresh XLA compiles (``executable_cache.misses_total == 0``,
  ``writes_total == 0``, counters pinned).
- **Warm bundle** (``jit.warmup``): record -> export -> load -> prewarm
  round-trips; a truncated/corrupt/over-versioned bundle falls back to
  cold compile with a counted ``warmup.failures_total{reason}``;
  pre-warm pre-populates the CapturedStep cache so the FIRST batch
  runs captured.
- **Weight hot-swap** (``GenerationServer.swap_weights``): applied
  between decode steps on the loop thread — a same-weights swap
  mid-stream leaves the greedy stream BIT-equal across the boundary
  (nothing dropped or corrupted), twin engines swapped to the same new
  weights stay in lockstep (the logits switch is a pure function of
  the new weights + shared pre-swap KV), allocator invariants hold,
  a weight-sharing draft re-aliases in the same swap, and a
  shape-mismatched checkpoint is rejected with the old weights intact.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Model
from paddle_tpu.jit import warmup
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import GenerationServer, PagedLlamaDecodeEngine
from paddle_tpu.utils import fault_injection as fi

CFG = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=2, use_flash_attention=False)
GEO = dict(max_slots=2, max_seq=128, block_size=8, prefill_chunk=8)


@pytest.fixture(scope="module", autouse=True)
def module_cache(tmp_path_factory):
    """One shared persistent executable cache for the whole module: the
    tiny llama engines these tests build all compile the SAME programs,
    so with the cache on, engine #2..N deserialize from disk instead of
    recompiling — the feature under test keeping its own tests fast.
    Per-test counter assertions still hold: they measure deltas."""
    d = str(tmp_path_factory.mktemp("hot_start_module_cache"))
    paddle.set_flags({"FLAGS_executable_cache_dir": d})
    warmup.ensure_executable_cache()
    try:
        yield d
    finally:
        paddle.set_flags({"FLAGS_executable_cache_dir": ""})
        warmup.ensure_executable_cache()


@pytest.fixture
def cache_dir(tmp_path, module_cache):
    """Enable the executable cache in a throwaway dir for one test
    (isolated counters/artifacts, e.g. for poisoning) and restore the
    module-wide cache afterwards (the next compile seam's ensure()
    call re-reads the flag, so flipping it back suffices)."""
    d = str(tmp_path / "xla_cache")
    paddle.set_flags({"FLAGS_executable_cache_dir": d})
    warmup.ensure_executable_cache()
    try:
        yield d
    finally:
        paddle.set_flags({"FLAGS_executable_cache_dir": module_cache})
        warmup.ensure_executable_cache()


def _model_a():
    paddle.seed(7)
    return LlamaForCausalLM(LlamaConfig.tiny(**CFG))


def _model_b():
    paddle.seed(13)
    return LlamaForCausalLM(LlamaConfig.tiny(**CFG))


def _hapi_model(seed=0):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 3))
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.Adam(
        learning_rate=0.01, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss())
    return m


def _toy_batch():
    rng = np.random.default_rng(0)
    return (rng.normal(size=(8, 4)).astype(np.float32),
            rng.integers(0, 3, 8).astype(np.int64))


def _pool_invariants(kv):
    st = kv.stats()
    owned = sum(len(b) for b in kv._owned.values())
    shared = sum(len(b) for b in kv._shared.values())
    # three-way partition: free / privately-owned / held by the
    # prefix radix tree (aliased blocks live in the tree, counted
    # once however many slots map them)
    assert st["blocks_free"] + owned + st["blocks_cached"] \
        == kv.num_blocks
    assert st["blocks_reserved"] == sum(kv._reserved.values())
    mapped = int((kv.block_tables >= 0).sum())
    assert mapped == owned + shared
    for row in kv.block_tables:
        row = row[row >= 0]
        assert len(set(row.tolist())) == len(row)
    kv.check_invariants()


# ---------------------------------------------------------------------------
# persistent executable cache
# ---------------------------------------------------------------------------

class TestExecutableCache:
    def test_flag_off_is_noop(self, module_cache):
        paddle.set_flags({"FLAGS_executable_cache_dir": ""})
        try:
            assert warmup.ensure_executable_cache() is False
        finally:
            paddle.set_flags(
                {"FLAGS_executable_cache_dir": module_cache})
            warmup.ensure_executable_cache()

    def test_roundtrip_and_poisoned_entry(self, cache_dir):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.jit.sot import capture_jit

        fn = capture_jit(lambda x: x * 2 + 1, name="hot_start_probe")
        x = jnp.asarray(np.arange(6, dtype=np.float32))
        before = warmup.cache_stats()
        np.testing.assert_allclose(np.asarray(fn(x)),
                                   np.arange(6) * 2 + 1)
        mid = warmup.cache_stats()
        assert mid["writes"] > before["writes"]
        assert mid["misses"] > before["misses"]
        # a fresh process re-traces but reads the artifact from disk:
        # clear_caches simulates the restart inside this process
        jax.clear_caches()
        np.testing.assert_allclose(np.asarray(fn(x)),
                                   np.arange(6) * 2 + 1)
        after = warmup.cache_stats()
        assert after["hits"] > mid["hits"]
        assert after["writes"] == mid["writes"]
        # poison EVERY cache artifact: the next compile must degrade to
        # a counted miss + fresh compile, never crash
        poisoned = 0
        for root, _dirs, files in os.walk(cache_dir):
            for name in files:
                with open(os.path.join(root, name), "wb") as f:
                    f.write(b"\x00poison\xff" * 8)
                poisoned += 1
        assert poisoned > 0
        jax.clear_caches()
        import warnings as _warnings
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            np.testing.assert_allclose(np.asarray(fn(x)),
                                       np.arange(6) * 2 + 1)
        final = warmup.cache_stats()
        assert final["misses"] > after["misses"]


# ---------------------------------------------------------------------------
# warm bundle: record / export / load / prewarm
# ---------------------------------------------------------------------------

class TestWarmBundle:
    def test_record_export_prewarm_captured_step(self, tmp_path):
        # other suite tests' captured steps (different models) are in
        # the cumulative recording; this test pins THIS run's round
        # trip, so start from a clean manifest — replaying a foreign
        # geometry into m2 is a counted failure by design
        warmup.clear_recorded()
        X, y = _toy_batch()
        m = _hapi_model()
        losses = [float(m.train_batch([X], [y])[0])
                  for _ in range(3)]
        ref = losses[0]  # same-point comparison for m2's FIRST step
        entries = [e for e in warmup.recorded()
                   if e["kind"] == "captured_step"]
        assert entries and entries[-1]["build"] == "train"
        assert entries[-1]["sig"] is not None
        path = warmup.export_bundle(str(tmp_path / "wb.json"))
        bundle = warmup.load_bundle(path)
        assert bundle["entries"]

        m2 = _hapi_model()
        out = warmup.prewarm(bundle, captured=m2._captured or
                             m2._capture_engine())
        assert out["programs"] >= 1 and out["failures"] == 0
        # the FIRST batch runs captured: no first-sighting eager step,
        # no fresh program build
        loss2 = m2.train_batch([X], [y])
        eng = m2._captured
        assert eng.stats["eager_steps"] == 0
        assert eng.stats["compiles"] == 0
        assert eng.stats["captured_steps"] == 1
        assert eng.stats["cache_hits"] == 1
        np.testing.assert_allclose(float(loss2[0]), ref, rtol=1e-5)

    def test_prepare_warm_bundle_kwarg(self, tmp_path):
        X, y = _toy_batch()
        m = _hapi_model()
        for _ in range(3):
            m.train_batch([X], [y])
        path = warmup.export_bundle(str(tmp_path / "wb.json"))
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(),
                            nn.Linear(16, 3))
        m2 = Model(net)
        m2.prepare(optimizer=paddle.optimizer.Adam(
            learning_rate=0.01, parameters=net.parameters()),
            loss=nn.CrossEntropyLoss(), warm_bundle=path)
        m2.train_batch([X], [y])
        assert m2._captured.stats["eager_steps"] == 0

    def test_prewarm_serving_programs(self):
        mA = _model_a()
        eng = PagedLlamaDecodeEngine(mA, **GEO)
        ref = eng.generate([1, 2, 3, 4], max_new_tokens=6)
        path = warmup.export_bundle()
        eng2 = PagedLlamaDecodeEngine(mA, **GEO)
        out = warmup.prewarm(path, engine=eng2)
        # decode + at least one prefill bucket replayed
        assert out["programs"] >= 2 and out["failures"] == 0
        assert eng2.generate([1, 2, 3, 4], max_new_tokens=6) == ref

    def test_spec_entries_skipped_without_draft(self):
        mA = _model_a()
        eng = PagedLlamaDecodeEngine(mA, **GEO)
        eng.attach_draft(eng.make_draft(mA, num_layers=1),
                         spec_tokens=3)
        srv = GenerationServer(eng)  # the loop runs spec_step
        srv.generate([1, 2, 3, 4], max_new_tokens=6)
        srv.shutdown()
        bundle = warmup.load_bundle(warmup.export_bundle())
        kinds = {e["meta"]["program"] for e in bundle["entries"]
                 if e["kind"] == "serving"}
        assert {"spec_draft", "spec_verify"} <= kinds
        plain = PagedLlamaDecodeEngine(mA, **GEO)  # no draft attached
        out = warmup.prewarm(bundle, engine=plain)
        assert out["failures"] == 0 and out["skipped"] >= 2


class TestBundleFaults:
    @staticmethod
    def _reason_count(reason):
        from paddle_tpu.jit.warmup import _M_failures
        return _M_failures.value(reason=reason)

    def test_truncated_bundle_falls_back(self, tmp_path):
        path = warmup.export_bundle(str(tmp_path / "wb.json"))
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[:max(4, len(blob) // 3)])
        before = self._reason_count("corrupt")
        assert warmup.load_bundle(path) is None
        assert self._reason_count("corrupt") == before + 1
        # boot continues cold: prewarm of the damaged bundle is a no-op
        out = warmup.prewarm(path, captured=None, engine=None)
        assert out == {"programs": 0, "failures": 0, "skipped": 0}

    def test_missing_bundle_counted(self, tmp_path):
        before = self._reason_count("missing")
        assert warmup.load_bundle(str(tmp_path / "nope.json")) is None
        assert self._reason_count("missing") == before + 1

    def test_version_gate(self, tmp_path):
        path = str(tmp_path / "future.json")
        with open(path, "w") as f:
            json.dump({"__paddle_tpu_warm_bundle__": 999,
                       "entries": []}, f)
        before = self._reason_count("version")
        assert warmup.load_bundle(path) is None
        assert self._reason_count("version") == before + 1

    def test_truncated_write_leaves_no_bundle(self, tmp_path):
        path = str(tmp_path / "wb.json")
        with fi.injected("warmup.write", truncate_at=16):
            with pytest.raises(Exception):
                warmup.export_bundle(path)
        assert not os.path.exists(path)
        assert not [n for n in os.listdir(tmp_path)
                    if n.startswith("wb.json.tmp")]

    def test_unreplayable_entry_counted_not_fatal(self):
        mA = _model_a()
        eng = PagedLlamaDecodeEngine(mA, **GEO)
        bundle = {"__paddle_tpu_warm_bundle__": 1, "entries": [
            {"kind": "serving", "name": "x",
             "meta": {"program": "prefill", "bucket": -3}},
            {"kind": "captured_step", "name": "y", "build": "bogus"},
            "not-a-dict"]}
        before = self._reason_count("program")
        out = warmup.prewarm(bundle, captured=object(), engine=eng)
        assert out["failures"] >= 1
        assert self._reason_count("program") >= before + 1
        # the engine still serves (cold) after the failed pre-warm
        assert len(eng.generate([1, 2], max_new_tokens=3)) == 3

    def test_stale_geometry_bundle_degrades_counted(self):
        """Freshness check (ISSUE 15): a bundle recorded by a replica
        with a DIFFERENT serving geometry must not be silently
        replayed — its entries would compile fresh programs at boot
        while the counters claim warmth. Every serving entry fails as
        reason=stale and the engine still boots (cold)."""
        warmup.clear_recorded()
        mA = _model_a()
        eng = PagedLlamaDecodeEngine(mA, **GEO)
        eng.generate([1, 2, 3], max_new_tokens=4)
        bundle = warmup.load_bundle(warmup.export_bundle())
        n_serving = sum(1 for e in bundle["entries"]
                        if e["kind"] == "serving")
        assert n_serving >= 2  # decode + >= 1 prefill bucket
        # entries carry the recording geometry
        metas = [e["meta"] for e in bundle["entries"]
                 if e["kind"] == "serving"]
        assert all(m["layout"] == "paged"
                   and m["block_size"] == GEO["block_size"]
                   for m in metas)
        other = PagedLlamaDecodeEngine(mA, max_slots=2, max_seq=128,
                                      block_size=16, prefill_chunk=8)
        before = self._reason_count("stale")
        out = warmup.prewarm(bundle, engine=other)
        assert out["programs"] == 0
        assert out["failures"] == n_serving
        assert self._reason_count("stale") == before + n_serving
        # the MATCHING geometry still replays everything
        twin = PagedLlamaDecodeEngine(mA, **GEO)
        out2 = warmup.prewarm(bundle, engine=twin)
        assert out2["programs"] >= 2 and out2["failures"] == 0
        # precision: a replica differing ONLY in prefill chunk keeps
        # all its warmth — no program's shape depends on the chunk
        # (recorded buckets still fit under the larger live chunk)
        chunky = PagedLlamaDecodeEngine(mA, max_slots=2, max_seq=128,
                                        block_size=8, prefill_chunk=16)
        out3 = warmup.prewarm(bundle, engine=chunky)
        assert out3["programs"] >= 2 and out3["failures"] == 0

    def test_dense_vs_paged_layout_is_stale(self):
        """A paged replica's bundle into a dense engine (or vice
        versa) is a LAYOUT mismatch, not warmth."""
        from paddle_tpu.serving import LlamaDecodeEngine
        warmup.clear_recorded()
        mA = _model_a()
        dense = LlamaDecodeEngine(mA, max_slots=2, max_seq=128)
        dense.generate([1, 2], max_new_tokens=3)
        bundle = warmup.load_bundle(warmup.export_bundle())
        paged = PagedLlamaDecodeEngine(mA, **GEO)
        before = self._reason_count("stale")
        out = warmup.prewarm(bundle, engine=paged)
        assert out["programs"] == 0
        assert self._reason_count("stale") > before


# ---------------------------------------------------------------------------
# cache-dir GC by last-hit age
# ---------------------------------------------------------------------------

class TestCacheDirGC:
    def test_evicts_by_last_hit_age_only(self, tmp_path):
        """Old cache artifacts age out (counted); fresh entries,
        warm-bundle manifests and subdirectories are never touched."""
        from paddle_tpu.jit.warmup import _M_evicted
        d = tmp_path / "xla_cache"
        d.mkdir()
        old = d / "jit__decode-abc123"
        old.write_bytes(b"stale artifact")
        stamp = time.time() - 3 * 86400
        os.utime(old, (stamp, stamp))
        fresh = d / "jit__prefill-def456"
        fresh.write_bytes(b"fresh artifact")
        manifest = d / "warm_bundle.json"
        manifest.write_text("{}")
        os.utime(manifest, (stamp, stamp))  # old but a manifest
        sub = d / "subdir"
        sub.mkdir()
        before = _M_evicted.value()
        assert warmup.gc_cache_dir(max_age_days=1,
                                   directory=str(d)) == 1
        assert not old.exists()
        assert fresh.exists() and manifest.exists() and sub.exists()
        assert _M_evicted.value() == before + 1
        # disabled (the flag default) is a no-op
        assert warmup.gc_cache_dir(max_age_days=0,
                                   directory=str(d)) == 0
        assert warmup.gc_cache_dir(directory=str(d)) == 0
        assert fresh.exists()


# ---------------------------------------------------------------------------
# zero-downtime weight swap
# ---------------------------------------------------------------------------

class TestWeightSwapEngine:
    def test_twin_engines_stay_lockstep_through_swap(self):
        """Two identical engines decode in lockstep; both swap to the
        same NEW weights mid-stream and must STAY in lockstep (the
        post-swap step is a pure function of the new weights + the
        shared pre-swap KV) while diverging from an unswapped third —
        the logits switched at the step boundary."""
        mA, mB = _model_a(), _model_b()
        sd_b = mB.state_dict()
        engines = [PagedLlamaDecodeEngine(mA, **GEO) for _ in range(3)]
        prompt = [1, 2, 3, 4, 5]
        firsts = {eng.prefill(0, prompt, budget=40) for eng in engines}
        assert len(firsts) == 1
        pre = [[int(eng.step()[0]) for _ in range(3)]
               for eng in engines]
        assert pre[0] == pre[1] == pre[2]
        engines[0].swap_weights(sd_b)
        engines[1].swap_weights(sd_b)
        post = [[int(eng.step()[0]) for _ in range(8)]
                for eng in engines]
        assert post[0] == post[1]          # swap is deterministic
        assert post[0] != post[2]          # and actually took effect
        for eng in engines:
            _pool_invariants(eng._kv)
            eng.release(0)

    def test_engine_swap_rejects_shape_mismatch(self):
        mA = _model_a()
        eng = PagedLlamaDecodeEngine(mA, **GEO)
        ref = eng.generate([3, 2, 1], max_new_tokens=5)
        old_params = eng.params
        paddle.seed(5)
        wrong = LlamaForCausalLM(LlamaConfig.tiny(
            **dict(CFG, hidden_size=16, intermediate_size=32)))
        with pytest.raises(ValueError):
            eng.swap_weights(wrong.state_dict())
        assert eng.params is old_params
        missing = dict(mA.state_dict())
        missing.pop("llama.norm.weight")
        with pytest.raises(ValueError):
            eng.swap_weights(missing)
        assert eng.params is old_params
        assert eng.generate([3, 2, 1], max_new_tokens=5) == ref


class TestWeightSwapServer:
    def _serve(self, model, **kw):
        geo = dict(GEO, **kw)
        return GenerationServer(PagedLlamaDecodeEngine(model, **geo))

    def test_same_weights_swap_is_bit_transparent(self):
        """A mid-decode swap to IDENTICAL weights must leave the
        in-flight greedy stream bit-equal to a never-swapped run: no
        token dropped, duplicated or corrupted across the boundary."""
        mA = _model_a()
        ref_srv = self._serve(mA)
        prompt = list(range(1, 9))
        ref = ref_srv.generate(prompt, max_new_tokens=40)
        ref_srv.shutdown()

        srv = self._serve(mA)
        req = srv.submit(prompt, max_new_tokens=40)
        deadline = time.monotonic() + 30
        while len(req["out"]) < 4 and time.monotonic() < deadline:
            time.sleep(0.001)
        res = srv.swap_weights(mA.state_dict())
        assert res["seconds"] >= 0
        assert req["done"].wait(60)
        assert list(req["out"]) == ref
        assert srv.stats()["weight_swaps"] == 1
        _pool_invariants(srv.engine._kv)
        srv.shutdown()

    def test_mid_stream_swap_switches_weights(self):
        """A mid-decode swap to NEW weights: the request keeps
        streaming to its full budget (nothing dropped), the engine's
        tree is the new one, and a post-swap request matches a fresh
        engine booted on the new weights."""
        mA, mB = _model_a(), _model_b()
        sd_b = mB.state_dict()
        srv = self._serve(mA)
        prompt = [2, 4, 6, 8]
        first_a = srv.generate(prompt, max_new_tokens=2)[0]
        req = srv.submit(prompt, max_new_tokens=60)
        deadline = time.monotonic() + 30
        while len(req["out"]) < 4 and time.monotonic() < deadline:
            time.sleep(0.001)
        srv.swap_weights(sd_b)
        assert req["done"].wait(60)
        assert len(req["out"]) == 60
        assert req["out"][0] == first_a  # pre-swap prefix from A
        _pool_invariants(srv.engine._kv)
        # a fresh request now runs fully on B
        post = srv.generate(prompt, max_new_tokens=8)
        engB = PagedLlamaDecodeEngine(mB, **GEO)
        assert post == engB.generate(prompt, max_new_tokens=8)
        srv.shutdown()

    def test_server_swap_rejection_keeps_serving(self):
        from paddle_tpu.serving import _M_swap_rejected
        mA = _model_a()
        srv = self._serve(mA)
        ref = srv.generate([1, 2, 3], max_new_tokens=6)
        bad = dict(mA.state_dict())
        bad.pop("llama.norm.weight")
        before = _M_swap_rejected.value()
        with pytest.raises(ValueError):
            srv.swap_weights(bad)
        assert _M_swap_rejected.value() == before + 1
        assert srv.stats()["weight_swaps"] == 0
        assert srv.generate([1, 2, 3], max_new_tokens=6) == ref
        srv.shutdown()

    def test_draft_rolls_with_target(self):
        mA, mB = _model_a(), _model_b()
        eng = PagedLlamaDecodeEngine(mA, **GEO)
        eng.attach_draft(eng.make_draft(mA, num_layers=1),
                         spec_tokens=3)
        srv = GenerationServer(eng)
        srv.generate([1, 2, 3, 4], max_new_tokens=6)
        srv.swap_weights(mB.state_dict())
        draft = eng._draft
        assert draft.params["emb"] is eng.params["emb"]
        for i in range(draft.n_layers):
            for nm, leaf in draft.params["layers"][i].items():
                assert leaf is eng.params["layers"][i][nm]
        # post-swap speculative stream == plain engine on B (the spec
        # bit-equality contract survives the swap)
        out = srv.generate([9, 8, 7], max_new_tokens=8)
        plain = PagedLlamaDecodeEngine(mB, **GEO)
        assert out == plain.generate([9, 8, 7], max_new_tokens=8)
        _pool_invariants(eng._kv)
        _pool_invariants(draft._kv)
        srv.shutdown()

    def test_swap_from_checkpoint_manager_and_path(self, tmp_path):
        from paddle_tpu.framework.checkpoint import CheckpointManager
        mA, mB = _model_a(), _model_b()
        mgr = CheckpointManager(str(tmp_path / "ck"), keep_n=2)
        mgr.save({"model": mA.state_dict(), "step": 0}, step=0)
        path_b = mgr.save({"model": mB.state_dict(), "step": 1}, step=1)
        srv = self._serve(mA)
        srv.swap_weights(mgr)  # newest good checkpoint = B
        engB = PagedLlamaDecodeEngine(mB, **GEO)
        refB = engB.generate([5, 6, 7], max_new_tokens=6)
        assert srv.generate([5, 6, 7], max_new_tokens=6) == refB
        srv.swap_weights(path_b)  # explicit path form
        assert srv.generate([5, 6, 7], max_new_tokens=6) == refB
        assert srv.stats()["weight_swaps"] == 2
        srv.shutdown()

    def test_swap_after_shutdown_rejected(self):
        mA = _model_a()
        srv = self._serve(mA)
        srv.shutdown()
        with pytest.raises(RuntimeError):
            srv.swap_weights(mA.state_dict())


# ---------------------------------------------------------------------------
# the restart acceptance: second process = zero fresh XLA compiles
# ---------------------------------------------------------------------------

_WORKER = r'''
import json, os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["FLAGS_executable_cache_dir"] = os.environ["HS_CACHE_DIR"]
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Model
from paddle_tpu.jit import warmup
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import PagedLlamaDecodeEngine

bundle = os.environ.get("HS_BUNDLE") or None
paddle.seed(0)
net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 3))
m = Model(net)
m.prepare(optimizer=paddle.optimizer.Adam(
    learning_rate=0.01, parameters=net.parameters()),
    loss=nn.CrossEntropyLoss(), warm_bundle=bundle)
rng = np.random.default_rng(0)
X = rng.normal(size=(8, 4)).astype(np.float32)
y = rng.integers(0, 3, 8).astype(np.int64)
loss = None
for _ in range(3):
    loss = m.train_batch([X], [y])
paddle.seed(1)
lm = LlamaForCausalLM(LlamaConfig.tiny(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    use_flash_attention=False))
eng = PagedLlamaDecodeEngine(lm, max_slots=1, max_seq=64, block_size=8,
                             prefill_chunk=8)
if bundle:
    warmup.prewarm(bundle, engine=eng)
toks = eng.generate([1, 2, 3], max_new_tokens=4)
export = os.environ.get("HS_EXPORT")
if export:
    warmup.export_bundle(export)
    # seal the bundle: persist the AOT-lowered flavors of every
    # recorded program so a pre-warmed boot is 100% disk hits
    warmup.prewarm(export, captured=m._captured, engine=eng)
print(json.dumps({"cache": warmup.cache_stats(),
                  "sot": {k: v for k, v in m._captured.stats.items()
                          if k != "fallbacks"},
                  "toks": [int(t) for t in toks],
                  "loss": float(loss[0])}))
'''


def _run_worker(cache_dir, bundle=None, export=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               HS_CACHE_DIR=str(cache_dir))
    env.pop("FLAGS_executable_cache_dir", None)
    env.pop("FLAGS_warmup_bundle", None)
    if bundle:
        env["HS_BUNDLE"] = str(bundle)
    if export:
        env["HS_EXPORT"] = str(export)
    r = subprocess.run([sys.executable, "-c", _WORKER], env=env,
                       capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_second_process_boots_with_zero_fresh_compiles(tmp_path):
    """THE acceptance scenario: process 1 boots cold against an empty
    cache dir, trains a captured step and decodes tokens, exports the
    warm bundle. Process 2 — same cache dir, pre-warmed from the
    bundle — reaches its first captured train step AND its first
    decode token with ZERO fresh XLA compiles: every compile is a
    persistent-cache disk hit (misses == 0, writes == 0, counters
    pinned), the first train_batch runs captured (no first-sighting
    eager step), and the streams/losses are bit-identical."""
    cache = tmp_path / "xla_cache"
    cache.mkdir()
    bundle = cache / "warm_bundle.json"
    cold = _run_worker(cache, export=bundle)
    assert cold["cache"]["writes"] > 0
    assert cold["cache"]["misses"] > 0
    assert bundle.exists()

    warm = _run_worker(cache, bundle=bundle)
    assert warm["cache"]["misses"] == 0, warm
    assert warm["cache"]["writes"] == 0, warm
    assert warm["cache"]["hits"] > 0, warm
    # first batch ran captured: pre-warm pre-populated the program
    assert warm["sot"]["eager_steps"] == 0, warm
    assert warm["sot"]["compiles"] == 0, warm
    assert warm["sot"]["captured_steps"] == 3, warm
    # and the warm boot computes the same numbers
    assert warm["toks"] == cold["toks"]
    assert warm["loss"] == pytest.approx(cold["loss"], rel=1e-6)
