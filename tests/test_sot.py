"""dy2static / SOT: guarded compiled subgraphs with graph breaks.

ref contract: python/paddle/jit/sot/opcode_translator/executor/
opcode_executor.py (guards, graph-break fallback) + jit/dy2static — here
implemented at the op-dispatch level (see paddle_tpu/jit/sot.py).
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit.sot import BucketPolicy, SOTFunction


class TestRecordReplay:
    def test_branch_guards_and_no_python_reexecution(self):
        calls = {"n": 0}

        def f(x):
            calls["n"] += 1
            y = x * 2
            if (y.sum() > 0):
                return (y + 1) * 3
            return (y - 1) * 3

        sf = SOTFunction(f)
        xp = paddle.to_tensor(np.ones((2, 2), np.float32))
        xn = paddle.to_tensor(-np.ones((2, 2), np.float32))
        r1 = sf(xp)
        np.testing.assert_allclose(r1.numpy(), (np.ones((2, 2)) * 2 + 1) * 3)
        assert calls["n"] == 1
        r2 = sf(xp)                       # compiled replay
        assert calls["n"] == 1
        np.testing.assert_allclose(r2.numpy(), r1.numpy())
        r3 = sf(xn)                       # guard miss -> new path recorded
        assert calls["n"] == 2
        np.testing.assert_allclose(r3.numpy(),
                                   (-np.ones((2, 2)) * 2 - 1) * 3)
        sf(xn), sf(xp)                    # both paths replay
        assert calls["n"] == 2
        assert sf.cache_size() == 2

    def test_eager_static_equality_mlp_with_control_flow(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))

        def f(x):
            h = net(x)
            # data-dependent post-processing
            if (h.mean() > 0):
                return paddle.nn.functional.softmax(h, axis=-1)
            return paddle.nn.functional.sigmoid(h)

        sf = SOTFunction(f)
        for _ in range(3):
            x = paddle.to_tensor(
                np.random.randn(4, 8).astype(np.float32))
            np.testing.assert_allclose(sf(x).numpy(), f(x).numpy(),
                                       rtol=1e-5, atol=1e-6)

    def test_while_loop_trip_count_paths(self):
        def g(x):
            s = x.sum()
            while (s < 10):
                s = s * 2 + 1
            return s

        sg = SOTFunction(g)
        assert float(sg(paddle.to_tensor(np.float32(1.0)))) == \
            float(g(paddle.to_tensor(np.float32(1.0))))
        assert float(sg(paddle.to_tensor(np.float32(9.0)))) == 19.0
        # replay both trip-count paths
        assert float(sg(paddle.to_tensor(np.float32(1.0)))) == 15.0
        assert float(sg(paddle.to_tensor(np.float32(9.0)))) == 19.0

    def test_live_parameter_updates_seen_by_replay(self):
        lin = nn.Linear(4, 4)
        sf = SOTFunction(lambda t: lin(t) + 0.0)
        x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        sf(x)
        lin.weight.set_value(np.zeros((4, 4), np.float32))
        out = sf(x)
        np.testing.assert_allclose(
            out.numpy(), np.tile(lin.bias.numpy(), (2, 1)), rtol=1e-5)

    def test_ext_tensor_guard(self):
        flag = paddle.to_tensor(np.float32(1.0))

        def f(x):
            if (flag):            # captured tensor steers python
                return x + 1
            return x - 1

        sf = SOTFunction(f)
        x = paddle.to_tensor(np.float32(0.0))
        assert float(sf(x)) == 1.0
        assert float(sf(x)) == 1.0
        flag.set_value(np.float32(0.0))   # replay must notice
        assert float(sf(x)) == -1.0


class TestFallbacks:
    def test_rng_falls_back_to_eager(self):
        def f(x):
            return paddle.nn.functional.dropout(x, 0.5, training=True)

        sf = SOTFunction(f)
        x = paddle.to_tensor(np.ones((64,), np.float32))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            o1 = sf(x)
            o2 = sf(x)
            assert any("not replayable" in str(v.message) for v in w)
        # eager fallback draws fresh randomness each call
        assert not np.array_equal(o1.numpy(), o2.numpy())

    def test_mutation_falls_back(self):
        def f(x):
            x[0] = 5.0            # in-place write
            return x * 2

        sf = SOTFunction(f)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = sf(paddle.to_tensor(np.zeros(3, np.float32)))
        np.testing.assert_allclose(out.numpy(), [10.0, 0.0, 0.0])

    def test_inner_backward_falls_back(self):
        lin = nn.Linear(2, 2)

        def f(x):
            y = lin(x).sum()
            y.backward()
            return lin.weight.grad

        sf = SOTFunction(f)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            g1 = sf(paddle.to_tensor(np.ones((1, 2), np.float32)))
            lin.clear_gradients()
            g2 = sf(paddle.to_tensor(np.ones((1, 2), np.float32)))
        np.testing.assert_allclose(g1.numpy(), g2.numpy())


class TestCaptureMetadata:
    """ISSUE 7: SOTFunction exposes segment/guard metadata so the
    capture planner (analysis.capture_plan) can read the recorded
    segmentation instead of re-deriving it."""

    def test_segments_guards_and_op_names(self):
        def f(x):
            y = x * 2
            if (y.sum() > 0):
                return y + 1
            return y - 1

        sf = SOTFunction(f)
        sf(paddle.to_tensor(np.ones((2, 2), np.float32)))
        sf(paddle.to_tensor(-np.ones((2, 2), np.float32)))
        md = sf.capture_metadata()
        assert md["cache_entries"] == 2
        paths = [p for p in md["paths"] if p["kind"] == "compiled"]
        assert len(paths) == 2
        for p in paths:
            # one guard (the branch) splitting the segments
            assert len(p["guards"]) == 1
            assert p["guards"][0]["kind"] in ("item", "numpy")
            assert len(p["segments"]) >= 2
            ops = [o for seg in p["segments"] for o in seg["ops"]]
            assert "multiply" in ops, ops
        assert md["fallback_reasons"] == {}

    def test_fallback_reasons_surface(self):
        def f(x):
            return paddle.nn.functional.dropout(x, 0.5, training=True)

        sf = SOTFunction(f)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            sf(paddle.to_tensor(np.ones((8,), np.float32)))
        md = sf.capture_metadata()
        assert any("RNG" in r for r in md["fallback_reasons"]), md
        assert any(p["kind"] == "eager" for p in md["paths"])

    def test_planner_attaches_sot_metadata(self):
        from paddle_tpu import analysis

        def f(x):
            return x * 2 + 1

        sf = SOTFunction(f)
        sf(paddle.to_tensor(np.ones((4,), np.float32)))
        plan = analysis.capture_plan(sf, dynamic=False)
        assert plan.sot is not None
        assert plan.sot["cache_entries"] == 1
        assert "sot:" in plan.render()


class TestCachePolicy:
    def test_lru_bounded(self):
        paddle.set_flags({"FLAGS_sot_cache_size": 4})
        try:
            sf = SOTFunction(lambda t: t + 1)
            for L in range(1, 10):
                sf(paddle.to_tensor(np.ones((L,), np.float32)))
            assert sf.cache_size() == 4
        finally:
            paddle.set_flags({"FLAGS_sot_cache_size": 64})

    def test_bucketing_bounds_varlen_compiles(self):
        bp = BucketPolicy({0: {1: "pow2"}}, pad_value=0)
        sf = SOTFunction(lambda t: (t * 2).sum(axis=1), bucket_policy=bp)
        for L in (3, 4, 5, 7, 6, 8, 5, 3):
            out = sf(paddle.to_tensor(np.ones((2, L), np.float32)))
            np.testing.assert_allclose(out.numpy(), np.full(2, 2.0 * L))
        assert sf.cache_size() == 2      # buckets 4 and 8 only

    def test_explicit_bucket_list(self):
        bp = BucketPolicy({0: {0: [16, 32]}}, pad_value=-100)
        seen = []

        def f(t):
            seen.append(t.shape[0])
            return t.sum()

        sf = SOTFunction(f, bucket_policy=bp)
        sf(paddle.to_tensor(np.zeros(10, np.float32)))
        sf(paddle.to_tensor(np.zeros(20, np.float32)))
        assert seen == [16, 32]


class TestToStaticIntegration:
    def test_default_is_sot(self):
        @paddle.jit.to_static
        def k(x):
            if (x.mean() > 0):
                return x * 10
            return x * -10

        assert float(k(paddle.to_tensor(np.float32(2.0)))) == 20.0
        assert float(k(paddle.to_tensor(np.float32(-2.0)))) == 20.0

    def test_full_graph_mode_still_works(self):
        paddle.seed(0)
        net = nn.Linear(4, 4)
        st = paddle.jit.to_static(net, full_graph=True)
        x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        np.testing.assert_allclose(st(x).numpy(), net(x).numpy(),
                                   rtol=1e-5, atol=1e-6)


class TestReviewFixes:
    def test_training_through_replay(self):
        """Replayed calls must stay differentiable: params receive grads
        and the model trains past step 1 (review finding #1)."""
        paddle.seed(0)
        net = nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters())

        @paddle.jit.to_static
        def forward(x, y):
            out = net(x)
            if (out.mean() < 1e6):     # graph break in the middle
                pred = paddle.tanh(out)
            else:
                pred = out
            return ((pred - y) ** 2).mean()

        x = paddle.to_tensor(np.random.randn(16, 4).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(16, 1).astype(np.float32) * .1)
        losses = []
        for _ in range(6):
            loss = forward(x, y)
            loss.backward()
            assert net.weight.grad is not None
            assert float(np.abs(net.weight.grad.numpy()).max()) > 0
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_inplace_op_falls_back(self):
        def f(x):
            x.add_(1.0)
            return x * 2

        sf = SOTFunction(f)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            o1 = sf(paddle.to_tensor(np.zeros(3, np.float32)))
            o2 = sf(paddle.to_tensor(np.zeros(3, np.float32)))
        np.testing.assert_allclose(o1.numpy(), [2.0, 2.0, 2.0])
        np.testing.assert_allclose(o2.numpy(), [2.0, 2.0, 2.0])

    def test_inplace_activation_falls_back(self):
        import paddle_tpu.nn.functional as F

        def f(x):
            return F.relu_(x * 1.0) + 1

        sf = SOTFunction(f)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            o = sf(paddle.to_tensor(np.array([-2.0, 2.0], np.float32)))
            o2 = sf(paddle.to_tensor(np.array([-2.0, 2.0], np.float32)))
        np.testing.assert_allclose(o.numpy(), [1.0, 3.0])
        np.testing.assert_allclose(o2.numpy(), [1.0, 3.0])

    def test_nested_sot(self):
        inner = SOTFunction(lambda x: x * 2)
        outer = SOTFunction(lambda x: inner(x) + 1)
        # prime inner's own cache first
        a = paddle.to_tensor(np.float32(3.0))
        assert float(inner(a)) == 6.0
        assert float(outer(a)) == 7.0
        assert float(outer(paddle.to_tensor(np.float32(5.0)))) == 11.0
        # replay path of outer covers the inner ops
        assert float(outer(paddle.to_tensor(np.float32(4.0)))) == 9.0

    def test_guard_on_input_tensor(self):
        def f(x):
            v = x.item()          # break on the INPUT itself
            return x + v

        sf = SOTFunction(f)
        assert float(sf(paddle.to_tensor(np.float32(2.0)))) == 4.0
        assert float(sf(paddle.to_tensor(np.float32(2.0)))) == 4.0
        assert float(sf(paddle.to_tensor(np.float32(3.0)))) == 6.0

    def test_guard_on_earlier_segment_tensor(self):
        def f(x):
            c = x.sum()
            bool(c > 0)           # break 1 (produced in segment 0)
            y = x * 2
            bool(c < 100)         # break 2 on segment-0 tensor
            return y + c

        sf = SOTFunction(f)
        xin = paddle.to_tensor(np.ones(3, np.float32))
        np.testing.assert_allclose(sf(xin).numpy(), [5.0, 5.0, 5.0])
        np.testing.assert_allclose(sf(xin).numpy(), [5.0, 5.0, 5.0])

    def test_raw_array_literal_signature(self):
        def f(x, mask):
            return (x * paddle.to_tensor(mask)).sum()

        sf = SOTFunction(f)
        x = paddle.to_tensor(np.ones(2000, np.float32))
        m1 = np.zeros(2000, np.float32)
        m1[0] = 1
        m2 = np.zeros(2000, np.float32)
        m2[1:3] = 1
        assert float(sf(x, m1)) == 1.0
        assert float(sf(x, m2)) == 2.0   # same shape/repr, different bytes
        assert float(sf(x, m1)) == 1.0

    def test_layer_to_static_keeps_layer_api(self):
        net = nn.Linear(3, 3)
        ret = paddle.jit.to_static(net)
        assert ret is net
        assert len(net.parameters()) == 2
        x = paddle.to_tensor(np.random.randn(2, 3).astype(np.float32))
        out = net(x)
        out2 = net(x)
        np.testing.assert_allclose(out.numpy(), out2.numpy(), rtol=1e-6)


class TestReviewFixes2:
    def test_train_eval_mode_separates_cache(self):
        """net.eval() trace must not replay for net.train() calls (review:
        mode is part of the signature, like the reference's attribute
        guards)."""
        net = nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5))
        sf = SOTFunction(lambda t: net(t))
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        net.eval()
        e1 = sf(x)
        e2 = sf(x)
        np.testing.assert_allclose(e1.numpy(), e2.numpy())  # deterministic
        net.train()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            t1 = sf(x)
            t2 = sf(x)
        # train mode: dropout live (eager fallback), differs from eval out
        assert not np.allclose(t1.numpy(), e1.numpy())
        assert not np.allclose(t1.numpy(), t2.numpy())
        net.eval()
        e3 = sf(x)  # eval path still compiled and correct
        np.testing.assert_allclose(e3.numpy(), e1.numpy())

    def test_amp_replay_reproduces_autocast(self):
        net = nn.Linear(16, 16)
        sf = SOTFunction(lambda t: net(t))
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        with paddle.amp.auto_cast(level="O2"):
            a1 = sf(x)   # record under AMP
            a2 = sf(x)   # replay under AMP
        np.testing.assert_allclose(a1.numpy(), a2.numpy())
        f1 = sf(x)       # records a separate non-AMP path
        f2 = sf(x)
        np.testing.assert_allclose(f1.numpy(), f2.numpy(), rtol=1e-6)
        # AMP output is bf16-rounded -> differs from the fp32 path
        assert a1.numpy().dtype != f1.numpy().dtype or \
            not np.array_equal(a1.numpy(), f1.numpy())

    def test_eager_branch_does_not_evict_compiled_sibling(self):
        flag = paddle.to_tensor(np.float32(1.0))

        def f(x):
            if (flag):
                return x * 2          # pure branch
            return paddle.nn.functional.dropout(x, 0.5)  # rng branch

        sf = SOTFunction(f)
        x = paddle.to_tensor(np.ones((8,), np.float32))
        r1 = sf(x)
        np.testing.assert_allclose(r1.numpy(), 2.0)
        flag.set_value(np.float32(0.0))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            sf(x)                     # rng branch -> eager marker
        flag.set_value(np.float32(1.0))
        calls = sf.cache_size()
        r3 = sf(x)                    # compiled pure path must survive
        np.testing.assert_allclose(r3.numpy(), 2.0)
        assert sf.cache_size() == calls  # replayed, not re-recorded


_GLOBAL_NET = None


class TestReviewFixes3:
    def test_mutated_numpy_arg_not_stale(self):
        sf = SOTFunction(lambda t, c: t * paddle.to_tensor(np.asarray(c)))
        x = paddle.to_tensor(np.full(4, 3.0, np.float32))
        buf = np.ones(4, np.float32)
        np.testing.assert_allclose(sf(x, buf).numpy(), 3.0)
        buf[:] = 2.0                      # in-place mutation
        np.testing.assert_allclose(sf(x, buf).numpy(), 6.0)

    def test_global_layer_mode_tracked(self):
        global _GLOBAL_NET
        _GLOBAL_NET = nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5))

        def f(t):
            return _GLOBAL_NET(t)

        sf = SOTFunction(f)
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        _GLOBAL_NET.eval()
        e1 = sf(x)
        _GLOBAL_NET.train()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            t1 = sf(x)
        assert not np.allclose(t1.numpy(), e1.numpy())

    def test_amp_custom_lists_in_signature(self):
        net = nn.Linear(16, 16)
        sf = SOTFunction(lambda t: net(t))
        x = paddle.to_tensor(np.random.randn(2, 16).astype(np.float32))
        with paddle.amp.auto_cast(level="O1"):
            a = sf(x)
        with paddle.amp.auto_cast(level="O1",
                                  custom_black_list={"matmul", "linear"}):
            b = sf(x)
        # different cast regimes must be distinct cache entries
        assert sf.cache_size() >= 2


def test_speculative_replay_nan_guard_rollback():
    """Wrong-path speculation must neither crash the call (NaN check
    tripping on discarded garbage) nor leak flags into the global
    pending NaN queue; the re-recorded branch serves the right result."""
    import paddle_tpu as paddle
    from paddle_tpu.core import autograd as ag
    from paddle_tpu.jit.sot import sot_compile

    # stride 1 = immediate-raise mode; stride 4 = batched-queue mode
    # (the queue isolation/rollback only has work to do in the latter)
    for stride in (1, 4):
        paddle.set_flags({"FLAGS_check_nan_inf": True,
                          "FLAGS_check_nan_inf_stride": stride})
        try:
            @sot_compile
            def f(x):
                if bool((x.min() > 0).numpy()):
                    return paddle.log(x)
                return x * 2.0

            pos = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
            neg = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
            f(pos)                                 # record positive path
            np.testing.assert_allclose(f(pos).numpy(),
                                       np.log([1.0, 2.0]),
                                       rtol=1e-6)  # replay it
            # guard miss: log(neg) speculated, discarded, re-recorded
            np.testing.assert_allclose(f(neg).numpy(), [-2.0, 4.0],
                                       rtol=1e-6)
            np.testing.assert_allclose(f(neg).numpy(), [-2.0, 4.0],
                                       rtol=1e-6)  # replay negative path
            # discarded-speculation flags must not leak into the queue
            assert not any(np.asarray(fl).any()
                           for _, _, fl in ag._nan_pending), \
                ag._nan_pending
            ag.flush_nan_checks()                  # must not raise
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False,
                              "FLAGS_check_nan_inf_stride": 1})
