"""Fusion III — whole-step program capture (ISSUE 10).

The SOT plane (jit/sot.py) executes the capture plan PR 7 proved
CONSISTENT: hapi.Model train/eval batches and jit.TrainStep run as ONE
cached, buffer-donated executable (CapturedStep); SOTFunction replays
recorded paths through lazily-compiled segments with speculatively
validated guards; every unreplayable event falls back to per-chain
eager fusion with a counted reason. Pinned here:

- guard miss -> discard-speculated-tail -> retrace is bit-identical to
  eager, and counted (sot.guard_misses_total / retraces_total);
- captured training -> CheckpointManager restore -> continue matches
  the uncaptured (FLAGS_sot_capture=0) run;
- held ``p.detach()`` snapshots survive donated captured steps (the
  PR 5 alias-registry contract, now under SOT);
- fallbacks are total, counted by reason, and flight-journaled;
- BucketPolicy bounds the captured-executable set for varlen batches.

(The llama acceptance — audit-asserted zero syncs / <= a handful of
flushes / <= 3 executables inside a captured ``Model.fit`` step — lives
in tests/test_capture_plan.py::test_captured_fit_step_runs_dispatch_free
next to the planner contract it closes.)
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Model
from paddle_tpu.jit.sot import BucketPolicy, CapturedStep, SOTFunction
from paddle_tpu.observability import flight
from paddle_tpu.observability import metrics as om


def _sot_snap():
    return dict(om.snapshot().get("sot", {}))


def _toy_data(n=32, din=4, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, din)).astype(np.float32)
    W = rng.normal(size=(din, classes)).astype(np.float32)
    y = (X @ W).argmax(-1).astype(np.int64)
    return X, y


def _model(lr=0.01, seed=0):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 3))
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.Adam(
        learning_rate=lr, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss())
    return m


def _run_steps(m, X, y, steps, bs=8, start=0):
    losses = []
    for i in range(start, start + steps):
        sl = slice((i * bs) % len(X), (i * bs) % len(X) + bs)
        loss = m.train_batch([X[sl]], [y[sl]])
        losses.append(float(loss[0]))  # the log boundary fetch
    return losses


def _total(v):
    """A labeled counter snapshots as {label: n}; unlabeled as n."""
    return sum(v.values()) if isinstance(v, dict) else v


class TestCapturedTraining:
    def test_steady_state_is_one_executable(self):
        X, y = _toy_data()
        m = _model()
        before = _sot_snap()
        losses = _run_steps(m, X, y, 8)
        after = _sot_snap()
        eng = m._captured
        # compile policy: sighting -> compile -> hits (one signature)
        assert eng.stats["eager_steps"] == 1
        assert eng.stats["compiles"] == 1
        assert eng.stats["cache_hits"] == 6
        assert eng.stats["captured_steps"] == 7
        assert after["captured_steps_total"] - \
            before["captured_steps_total"] == 7
        assert eng.stats["fallbacks"] == {}
        assert losses[-1] < losses[0], losses

    def test_lazy_loss_is_a_device_tensor(self):
        X, y = _toy_data()
        m = _model()
        out = m.train_batch([X[:8]], [y[:8]])
        from paddle_tpu.core.tensor import Tensor
        assert isinstance(out[0], Tensor)
        assert float(out[0]) > 0  # fetch works at the boundary

    def test_kill_switch_restores_eager_path(self):
        X, y = _toy_data()
        paddle.set_flags({"FLAGS_sot_capture": 0})
        try:
            m_off = _model()
            off = _run_steps(m_off, X, y, 6)
            assert m_off._captured.stats["captured_steps"] == 0
        finally:
            paddle.set_flags({"FLAGS_sot_capture": 1})
        m_on = _model()
        on = _run_steps(m_on, X, y, 6)
        assert m_on._captured.stats["captured_steps"] >= 4
        np.testing.assert_allclose(on, off, rtol=1e-5, atol=1e-6)
        for (k, p_on), p_off in zip(
                m_on.network.state_dict().items(),
                m_off.network.state_dict().values()):
            np.testing.assert_allclose(
                p_on.numpy(), p_off.numpy(), rtol=1e-5, atol=1e-6,
                err_msg=k)

    def test_checkpoint_restore_continue_matches_uncaptured(self,
                                                           tmp_path):
        from paddle_tpu.framework.checkpoint import CheckpointManager
        X, y = _toy_data()
        # reference: 6 uncaptured steps straight through
        paddle.set_flags({"FLAGS_sot_capture": 0})
        try:
            m_ref = _model()
            _run_steps(m_ref, X, y, 6)
        finally:
            paddle.set_flags({"FLAGS_sot_capture": 1})
        # captured: 3 steps -> checkpoint -> restore -> 3 more
        m1 = _model()
        _run_steps(m1, X, y, 3)
        cm = CheckpointManager(str(tmp_path))
        cm.save({"net": {k: paddle.to_tensor(v.numpy()) for k, v in
                         m1.network.state_dict().items()},
                 "opt": m1._optimizer.state_dict()}, step=3)
        del m1
        step, ckpt = cm.restore()
        assert step == 3
        m2 = _model()
        m2.network.set_state_dict(ckpt["net"])
        m2._optimizer.set_state_dict(ckpt["opt"])
        _run_steps(m2, X, y, 3, start=3)  # steps 4-6 resume mid-stream
        for (k, got), ref in zip(m2.network.state_dict().items(),
                                 m_ref.network.state_dict().values()):
            np.testing.assert_allclose(
                got.numpy(), ref.numpy(), rtol=1e-5, atol=1e-6,
                err_msg=k)

    def test_detach_snapshot_survives_donated_steps(self):
        X, y = _toy_data()
        m = _model()
        _run_steps(m, X, y, 3)  # warm: the next step is captured
        p = m.network[0].weight
        snap = p.detach()
        frozen = np.asarray(snap.numpy()).copy()
        _run_steps(m, X, y, 2)  # donating captured steps
        # the live param moved; the held snapshot did not (and its
        # buffer was not deleted under it by the donation)
        assert not np.allclose(p.numpy(), frozen)
        np.testing.assert_array_equal(snap.numpy(), frozen)

    def test_primed_grads_fall_back_and_accumulate(self):
        X, y = _toy_data()
        m = _model()
        _run_steps(m, X, y, 3)
        p = m.network[0].weight
        p.grad = paddle.to_tensor(np.ones(p.shape, np.float32))
        m.train_batch([X[:8]], [y[:8]])  # must take the eager path
        assert m._captured.stats["fallbacks"].get("pending_grads", 0) \
            >= 1

    def test_forward_hook_falls_back(self):
        X, y = _toy_data()
        m = _model()
        _run_steps(m, X, y, 3)
        seen = []
        h = m.network[0].register_forward_post_hook(
            lambda lyr, i, o: seen.append(1))
        try:
            m.train_batch([X[:8]], [y[:8]])
        finally:
            h.remove()
        assert seen, "the hook must actually run (eager path)"
        assert m._captured.stats["fallbacks"].get("hooks", 0) >= 1
        # hook removed: capture resumes on the cached program
        before = m._captured.stats["captured_steps"]
        m.train_batch([X[:8]], [y[:8]])
        assert m._captured.stats["captured_steps"] == before + 1

    def test_eval_capture_matches_eager(self):
        X, y = _toy_data()
        m = _model()
        _run_steps(m, X, y, 4)
        paddle.set_flags({"FLAGS_sot_capture": 0})
        try:
            eager = m.eval_batch([X[:8]], [y[:8]])
            eager_loss = float(eager["loss"])
        finally:
            paddle.set_flags({"FLAGS_sot_capture": 1})
        m.eval_batch([X[:8]], [y[:8]])          # sighting
        cap = m.eval_batch([X[:8]], [y[:8]])    # captured
        assert m._captured.stats["captured_steps"] >= 1
        np.testing.assert_allclose(float(cap["loss"]), eager_loss,
                                   rtol=1e-5)

    def test_signature_change_retraces_not_corrupts(self):
        X, y = _toy_data()
        m = _model()
        _run_steps(m, X, y, 4, bs=8)
        c0 = m._captured.stats["compiles"]
        # new batch shape = new signature: sighting then second compile
        for _ in range(3):
            m.train_batch([X[:4]], [y[:4]])
        assert m._captured.stats["compiles"] == c0 + 1
        # freezing a param flips the trainable set = another signature
        m.network[2].bias.stop_gradient = True
        b = m.network[2].bias.numpy().copy()
        for _ in range(3):
            m.train_batch([X[:4]], [y[:4]])
        np.testing.assert_array_equal(m.network[2].bias.numpy(), b)
        m.network[2].bias.stop_gradient = False


class TestSignatureSplit:
    def test_input_label_split_is_part_of_the_signature(self):
        """Same array shapes with a different input/label split must be
        DIFFERENT programs — a collision would run the wrong forward."""
        class TwoWay(nn.Layer):
            def forward(self, a, b=None):
                return a * 2.0 if b is None else a + b

        net = TwoWay()
        step = CapturedStep(net, None, None, strict=False, name="split")
        x = paddle.to_tensor(np.full((4,), 3.0, np.float32))
        y = paddle.to_tensor(np.full((4,), 10.0, np.float32))
        out1, _ = step.forward([x], [y])     # net(x), y is a label
        np.testing.assert_array_equal(out1.numpy(), 6.0)
        out2, _ = step.forward([x, y], [])   # net(x, y): same shapes!
        np.testing.assert_array_equal(out2.numpy(), 13.0)
        out3, _ = step.forward([x], [y])     # first program still right
        np.testing.assert_array_equal(out3.numpy(), 6.0)


class TestTrainStepWrapper:
    def test_trainstep_is_a_captured_step(self):
        from paddle_tpu.jit.api import TrainStep
        paddle.seed(0)
        net = nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters())
        step = TrainStep(net, lambda o, t: ((o - t) ** 2).mean(), opt)
        X = np.random.default_rng(0).normal(size=(16, 4)).astype(
            np.float32)
        Y = (X @ np.ones((4, 1), np.float32) * 0.5).astype(np.float32)
        losses = [float(step(X, Y)) for _ in range(10)]
        assert losses[-1] < losses[0] * 0.7, losses
        # TrainStep is explicit whole-step API: captures on call ONE
        # (no first-eager sighting), ignores the kill switch
        assert step._step.stats["compiles"] == 1
        assert step._step.stats["eager_steps"] == 0
        # slot state now lives on the optimizer (state_dict round-trip
        # covers compiled training)
        assert opt._states, "optimizer slot state must be shared"
        paddle.set_flags({"FLAGS_sot_capture": 0})
        try:
            assert float(step(X, Y)) > 0  # still runs captured
        finally:
            paddle.set_flags({"FLAGS_sot_capture": 1})

    def test_compile_stats_contract(self):
        from paddle_tpu.jit.api import TrainStep
        net = nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=net.parameters())
        step = TrainStep(net, lambda o, t: ((o - t) ** 2).mean(), opt)
        X = np.zeros((8, 4), np.float32)
        Y = np.zeros((8, 1), np.float32)
        stats = step.compile_stats(X, Y)
        assert stats is not None


class TestGuardMissRetrace:
    def test_guard_miss_discard_retrace_bit_identical(self):
        """The satellite contract: a guard miss discards the speculated
        tail (pure programs, no side effects) and the retraced branch
        serves results BIT-identical to plain eager execution."""
        def f(x):
            y = x * 3.0
            if (y.sum() > 0):
                return (y + 1.0) * 2.0
            return (y - 1.0) * 0.5

        sf = SOTFunction(f)
        pos = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        neg = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
        before = _sot_snap()
        sf(pos)                                    # record path A
        np.testing.assert_array_equal(sf(pos).numpy(), f(pos).numpy())
        mid = _sot_snap()
        # guard miss: path A speculated on neg, discarded, re-recorded
        np.testing.assert_array_equal(sf(neg).numpy(), f(neg).numpy())
        after = _sot_snap()
        assert after["guard_misses_total"] > \
            mid["guard_misses_total"]
        assert after["retraces_total"] > mid["retraces_total"]
        assert mid["guard_misses_total"] == \
            before.get("guard_misses_total", 0)
        # both branches replay bit-identically afterwards
        np.testing.assert_array_equal(sf(pos).numpy(), f(pos).numpy())
        np.testing.assert_array_equal(sf(neg).numpy(), f(neg).numpy())

    def test_segments_compile_lazily_on_second_replay(self):
        def g(x):
            y = x * 2.0
            bool(y.sum() > 0)  # break: two segments
            return y + 1.0

        sf = SOTFunction(g, name="lazy_seg")
        x = paddle.to_tensor(np.ones(3, np.float32))
        before = _sot_snap()
        sf(x)                                      # record
        sf(x)                                      # replay 1: un-jitted
        mid = _sot_snap()
        assert mid.get("segment_compiles_total", 0) == \
            before.get("segment_compiles_total", 0)
        sf(x)                                      # replay 2: compiles
        after = _sot_snap()
        compiled = after["segment_compiles_total"] - \
            mid.get("segment_compiles_total", 0)
        assert compiled >= 1
        ev = [e for e in flight.events(category="sot")
              if e["name"] == "segment_compile"
              and e["attrs"].get("fn") == "lazy_seg"]
        assert ev, "segment compiles must land in the flight journal"
        sf(x)                                      # replay 3: no growth
        assert _sot_snap()["segment_compiles_total"] == \
            after["segment_compiles_total"]

    def test_guard_budget_flag_forces_eager(self):
        def h(x):
            for _ in range(4):
                float(x.sum())      # 4 guards x 4B
                x = x + 1.0
            return x

        paddle.set_flags({"FLAGS_sot_guard_budget": 8})
        try:
            sf = SOTFunction(h)
            x = paddle.to_tensor(np.ones(3, np.float32))
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                sf(x)
            md = sf.capture_metadata()
            assert any("guard budget" in r
                       for r in md["fallback_reasons"]), md
        finally:
            paddle.set_flags({"FLAGS_sot_guard_budget": 512})


class TestFlightAndMetrics:
    def test_fallback_reason_counted_and_journaled(self):
        def f(x):
            return paddle.nn.functional.dropout(x, 0.5, training=True)

        before = _sot_snap()
        sf = SOTFunction(f, name="rng_fn")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            sf(paddle.to_tensor(np.ones(8, np.float32)))
        after = _sot_snap()
        assert _total(after["fallbacks_total"]) > _total(
            before.get("fallbacks_total", 0))
        cell = om.default_registry().get("sot.fallbacks_total")
        assert cell.value(reason="rng") >= 1
        ev = [e for e in flight.events(category="sot")
              if e["name"] == "fallback"
              and e["attrs"].get("fn") == "rng_fn"]
        assert ev and ev[-1]["attrs"]["reason"] == "rng"

    def test_capture_jit_accounts_and_respects_kill_switch(self):
        from paddle_tpu.jit.sot import capture_jit
        import jax.numpy as jnp
        step = capture_jit(lambda a: a * 2, name="unit.step")
        before = _sot_snap()
        step(jnp.ones((2,)))
        mid = _sot_snap()
        assert mid["captured_compiles_total"] == \
            before["captured_compiles_total"] + 1
        assert mid["captured_steps_total"] == \
            before["captured_steps_total"] + 1
        ev = [e for e in flight.events(category="sot")
              if e["name"] == "capture_compile"
              and e["attrs"].get("fn") == "unit.step"]
        assert ev
        paddle.set_flags({"FLAGS_sot_capture": 0})
        try:
            out = step(jnp.ones((2,)))  # behavior identical, count muted
            np.testing.assert_array_equal(np.asarray(out), 2.0)
        finally:
            paddle.set_flags({"FLAGS_sot_capture": 1})
        assert _sot_snap()["captured_steps_total"] == \
            mid["captured_steps_total"]

    def test_serving_decode_is_a_captured_step(self):
        """The serving decode body (clean capture plan checked in)
        routes through capture_jit: steady-state decode counts as
        captured steps."""
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.serving import LlamaDecodeEngine
        paddle.seed(0)
        eng = LlamaDecodeEngine(
            LlamaForCausalLM(LlamaConfig.tiny()), max_slots=2,
            max_seq=32)
        eng.prefill(0, np.array([1, 2, 3], np.int32))
        eng.prefill(1, np.array([4, 5], np.int32))
        before = _sot_snap()
        for _ in range(3):
            eng.step()
        after = _sot_snap()
        assert after["captured_steps_total"] - \
            before["captured_steps_total"] == 3


class TestBucketPolicy:
    def test_bucketed_captured_step_bounds_executables(self):
        """Varlen batches under a pow2 BucketPolicy share a BOUNDED
        captured-executable set (padding semantics are the caller's
        explicit policy, as documented)."""
        paddle.seed(0)
        net = nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.0,
                                   parameters=net.parameters())
        step = CapturedStep(
            net, lambda o: (o * 0.0).sum(), opt, strict=False,
            bucket_policy=BucketPolicy({0: {0: "pow2"}}, pad_value=0),
            name="bucketed")
        for n in (3, 4, 5, 7, 6, 8, 5, 3):
            x = paddle.to_tensor(np.ones((n, 4), np.float32))
            assert step.step([x], []) is not None
        # lengths 3..8 -> pow2 buckets {4, 8}: exactly two programs
        assert step.stats["compiles"] == 2, step.stats
