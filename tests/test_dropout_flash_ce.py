"""Tests for the BERT-bar perf pack: hash dropout, flash d=64 gating +
in-kernel dropout plumbing, and the big-vocab chunked cross-entropy route
(ref: dropout_kernel.cu philox dropout; flash_attn_kernel.cu p_dropout;
c_softmax_with_cross_entropy fused CE)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


class TestHashDropout:
    def test_mean_preserved_and_fraction(self):
        paddle.seed(0)
        x = paddle.ones([256, 256])
        y = F.dropout(x, p=0.25, training=True)
        yn = y.numpy()
        frac_kept = float((yn != 0).mean())
        assert abs(frac_kept - 0.75) < 0.02
        # upscale_in_train: kept entries are x/(1-p)
        np.testing.assert_allclose(yn[yn != 0], 1.0 / 0.75, rtol=1e-6)
        assert abs(float(yn.mean()) - 1.0) < 0.03

    def test_deterministic_per_seed(self):
        x = paddle.ones([64, 128])
        paddle.seed(7)
        a = F.dropout(x, p=0.5, training=True).numpy()
        paddle.seed(7)
        b = F.dropout(x, p=0.5, training=True).numpy()
        np.testing.assert_array_equal(a, b)
        paddle.seed(8)
        c = F.dropout(x, p=0.5, training=True).numpy()
        assert not np.array_equal(a, c)

    def test_grad_is_mask_over_keep(self):
        paddle.seed(3)
        x = paddle.to_tensor(np.random.default_rng(0).standard_normal(
            (32, 128)).astype(np.float32), stop_gradient=False)
        paddle.seed(11)
        y = F.dropout(x, p=0.4, training=True)
        y.sum().backward()
        g = x.grad.numpy()
        mask = (y.numpy() != 0).astype(np.float32)
        np.testing.assert_allclose(g, mask / 0.6, rtol=1e-5)

    def test_eval_passthrough_and_edges(self):
        x = paddle.to_tensor(np.random.default_rng(1).standard_normal(
            (8, 128)).astype(np.float32))
        np.testing.assert_array_equal(
            F.dropout(x, p=0.9, training=False).numpy(), x.numpy())
        np.testing.assert_array_equal(
            F.dropout(x, p=0.0, training=True).numpy(), x.numpy())
        assert float(np.abs(
            F.dropout(x, p=1.0, training=True).numpy()).max()) == 0.0

    def test_axis_mode_still_works(self):
        """axis dropout keeps the bernoulli path (mask broadcast along
        non-listed dims)."""
        paddle.seed(0)
        x = paddle.ones([16, 64])
        y = F.dropout(x, p=0.5, axis=0, training=True).numpy()
        # each row is all-kept or all-dropped
        rows = (y != 0).all(axis=1) | (y == 0).all(axis=1)
        assert rows.all()


class TestFusedCERoute:
    def _oracle(self, logits, labels, ignore_index=-100):
        f = logits.astype(np.float64)
        lse = np.log(np.exp(f - f.max(-1, keepdims=True)).sum(-1)) + \
            f.max(-1)
        per = lse - np.take_along_axis(
            f, np.maximum(labels, 0)[..., None], axis=-1)[..., 0]
        valid = labels != ignore_index
        return per[valid].mean()

    def test_big_vocab_matches_oracle(self):
        rng = np.random.default_rng(0)
        n, v = 64, 4096  # v >= 4096 engages the chunked route
        logits = rng.standard_normal((n, v)).astype(np.float32)
        labels = rng.integers(0, v, (n,)).astype(np.int64)
        got = float(F.cross_entropy(paddle.to_tensor(logits),
                                    paddle.to_tensor(labels)))
        np.testing.assert_allclose(got, self._oracle(logits, labels),
                                   rtol=1e-5)

    def test_big_vocab_ignore_index(self):
        rng = np.random.default_rng(1)
        n, v = 64, 4096
        logits = rng.standard_normal((n, v)).astype(np.float32)
        labels = rng.integers(0, v, (n,)).astype(np.int64)
        labels[::3] = -100
        got = float(F.cross_entropy(paddle.to_tensor(logits),
                                    paddle.to_tensor(labels)))
        np.testing.assert_allclose(got, self._oracle(labels=labels,
                                                     logits=logits),
                                   rtol=1e-5)

    def test_big_vocab_grad_matches_small_vocab_formula(self):
        """d_logits = (softmax - onehot)/N on the fused route == the
        unfused formula (checked against the v<4096 XLA path on a
        sliced problem is impossible, so check analytically)."""
        rng = np.random.default_rng(2)
        n, v = 16, 4096
        logits_np = rng.standard_normal((n, v)).astype(np.float32)
        labels_np = rng.integers(0, v, (n,)).astype(np.int64)
        t = paddle.to_tensor(logits_np, stop_gradient=False)
        loss = F.cross_entropy(t, paddle.to_tensor(labels_np))
        loss.backward()
        g = t.grad.numpy()
        f = logits_np.astype(np.float64)
        sm = np.exp(f - f.max(-1, keepdims=True))
        sm /= sm.sum(-1, keepdims=True)
        oh = np.zeros_like(sm)
        oh[np.arange(n), labels_np] = 1.0
        np.testing.assert_allclose(g, (sm - oh) / n, atol=1e-6)

    def test_3d_logits_route(self):
        rng = np.random.default_rng(3)
        b, l, v = 2, 8, 4096
        logits = rng.standard_normal((b, l, v)).astype(np.float32)
        labels = rng.integers(0, v, (b, l)).astype(np.int64)
        got = float(F.cross_entropy(paddle.to_tensor(logits),
                                    paddle.to_tensor(labels)))
        np.testing.assert_allclose(
            got, self._oracle(logits.reshape(-1, v), labels.reshape(-1)),
            rtol=1e-5)


class TestFlashD64Gate:
    def test_tiles_ok_accepts_d64(self):
        from paddle_tpu.ops.pallas.flash_attention import _tiles_ok
        assert _tiles_ok(512, 64, 128, 128)
        assert _tiles_ok(512, 128, 128, 128)
        assert not _tiles_ok(512, 48, 128, 128)
        assert not _tiles_ok(100, 64, 128, 128)

    def test_sdpa_dropout_seed_deterministic_fallback(self):
        """CPU fallback of flash_attention with dropout: same seed ->
        same output; p=0 matches the no-dropout oracle."""
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention, _sdpa_xla)
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((2, 16, 2, 8)).astype(
            np.float32))
        k = jnp.asarray(rng.standard_normal((2, 16, 2, 8)).astype(
            np.float32))
        v = jnp.asarray(rng.standard_normal((2, 16, 2, 8)).astype(
            np.float32))
        o0 = flash_attention(q, k, v, False, None, 0.0, None)
        np.testing.assert_allclose(np.asarray(o0),
                                   np.asarray(_sdpa_xla(q, k, v)),
                                   rtol=1e-6)
        a = flash_attention(q, k, v, False, None, 0.2, 5)
        b = flash_attention(q, k, v, False, None, 0.2, 5)
        c = flash_attention(q, k, v, False, None, 0.2, 6)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_sdpa_dropout_grad_fd_fallback(self):
        """Finite differences re-run the same seeded mask, so they give a
        true check of the dropout VJP on the fallback path."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.standard_normal((1, 8, 1, 4)).astype(
            np.float64))
        k = jnp.asarray(rng.standard_normal((1, 8, 1, 4)).astype(
            np.float64))
        v = jnp.asarray(rng.standard_normal((1, 8, 1, 4)).astype(
            np.float64))
        w = jnp.asarray(rng.standard_normal((1, 8, 1, 4)).astype(
            np.float64))

        def loss(qq):
            return jnp.sum(flash_attention(qq, k, v, True, None, 0.3, 9)
                           * w)

        g = jax.grad(loss)(q)
        # f32 arithmetic: FD quotient noise ~|L|*1e-7/eps — keep eps
        # large enough that 1% tolerance holds (mask is seed-only, so
        # perturbation never flips it)
        eps = 5e-3
        d = jnp.asarray(rng.standard_normal(q.shape))
        fd = (loss(q + eps * d) - loss(q - eps * d)) / (2 * eps)
        np.testing.assert_allclose(float(jnp.sum(g * d)), float(fd),
                                   rtol=1e-2)

    def test_mha_dropout_trains(self):
        """MultiHeadAttention with attn dropout>0 trains end-to-end on the
        CPU path (routing sanity for the fused-dropout attention gate)."""
        import paddle_tpu.nn as nn
        paddle.seed(0)
        mha = nn.MultiHeadAttention(32, 4, dropout=0.1)
        x = paddle.to_tensor(np.random.default_rng(0).standard_normal(
            (2, 16, 32)).astype(np.float32), stop_gradient=False)
        out = mha(x, x, x)
        out.sum().backward()
        assert mha.q_proj.weight.grad is not None
        assert np.isfinite(mha.q_proj.weight.grad.numpy()).all()
