"""Prefix-sharing KV cache (ISSUE 16): radix-tree block reuse with
copy-on-write.

Host-side allocator tests (jax-free: pure PagedKVCache churn) pin the
refcount/partition invariants, the boundary-only COW contract, commit
dedupe, LRU eviction under pressure and the FLAGS_serving_prefix_cache
off-path byte-equivalence. Engine tests pin the end-to-end promise:
aliased prefixes produce BIT-equal greedy streams (the whole point —
sharing must be invisible in the tokens), including over speculative
decode's accept/rollback and across a crash-recovery ``reset_state``.

Oracle strategy mirrors test_serving_paged.py: the module-scoped dense
engine (transitively pinned against hapi generate) provides memoized
reference streams; prefix-cache-off engines re-derive the SAME streams
so on/off equality is a three-way pin.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import LlamaDecodeEngine, PagedLlamaDecodeEngine
from paddle_tpu.serving_cache import PagedKVCache
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

CFG = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=2, use_flash_attention=False)


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    return LlamaForCausalLM(LlamaConfig.tiny(**CFG))


@pytest.fixture(scope="module")
def dense_ref(model):
    eng = LlamaDecodeEngine(model, max_slots=1, max_seq=256)
    cache = {}

    def ref(prompt, n_new):
        key = (tuple(int(t) for t in prompt), int(n_new))
        if key not in cache:
            cache[key] = eng.generate(list(key[0]), max_new_tokens=n_new)
        return cache[key]

    return ref


def _invariants(kv):
    """Full allocator probe: three-way physical partition, per-row
    table uniqueness, then the allocator's own assertion suite."""
    st = kv.stats()
    owned = sum(len(b) for b in kv._owned.values())
    assert st["blocks_free"] + owned + st["blocks_cached"] \
        == kv.num_blocks
    for row in kv.block_tables:
        row = row[row >= 0]
        assert len(set(row.tolist())) == len(row)
    kv.check_invariants()


# ---------------------------------------------------------------------------
# host allocator: radix tree refcounts, COW contract, eviction
# ---------------------------------------------------------------------------

P16 = list(range(1, 17))     # 4 full blocks at block_size 4
P8 = P16[:8]                 # 2 full blocks


def _kv(num_blocks=16, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq", 32)
    kw.setdefault("block_size", 4)
    return PagedKVCache(num_blocks=num_blocks, **kw)


class TestRadixAllocator:
    def test_refcount_churn_invariants(self):
        """Interleaved admit/commit/alias/truncate/release churn keeps
        every invariant at every step, and full drain leaves the tree
        cached at ref 0 with zero live/reserved blocks."""
        kv = _kv()
        assert kv.admit(0, 16, 20, token_ids=P16)
        _invariants(kv)
        assert kv.commit_prefix(0, P16, 16) == 4
        _invariants(kv)
        # aliasing admission while the owner is still live
        assert kv.admit(1, 16, 24, token_ids=P16)
        assert kv.matched_tokens(1) == 15            # full match: n-1
        assert kv.take_cow(1) is not None
        _invariants(kv)
        # divergent prompt sharing the first 2 blocks only
        assert kv.admit(2, 16, 16, token_ids=P8 + [90, 91, 92, 93,
                                                   94, 95, 96, 97])
        assert kv.matched_tokens(2) == 8
        assert kv.take_cow(2) is None                # not block-aligned
        _invariants(kv)
        kv.ensure_token(0, 16)                       # draw reservation
        _invariants(kv)
        kv.truncate(1, 8)                            # back into prefix
        _invariants(kv)
        for s in (0, 2, 1):
            kv.release(s)
            _invariants(kv)
        st = kv.stats()
        assert st["blocks_used"] == 0
        assert st["blocks_reserved"] == 0
        assert st["blocks_cached"] == st["blocks_evictable"] > 0
        assert st["prefix_hits"] == 2
        assert st["prefix_tokens_reused"] == 15 + 8

    def test_full_match_cow_accounting(self):
        """A block-aligned full-prompt match aliases all but the
        boundary block, which is cloned (one extra charged block) so
        the re-prefilled last token writes privately; the clone is
        handed out exactly once via take_cow."""
        kv = _kv(num_blocks=6)
        assert kv.admit(0, 8, 8, token_ids=P8)
        kv.commit_prefix(0, P8, 8)
        kv.release(0)
        free_before = kv.stats()["blocks_free"]
        assert kv.admit(1, 8, 8, token_ids=P8)
        assert kv.matched_tokens(1) == 7
        mv = kv.take_cow(1)
        assert mv is not None
        src, dst = mv
        assert kv._by_block[src].ref == 0            # boundary decref'd
        assert dst in kv._owned[1]
        assert kv.take_cow(1) is None                # consumed
        assert len(kv._shared[1]) == 1               # only block 0 aliased
        assert kv.stats()["blocks_free"] == free_before - 1
        _invariants(kv)
        kv.release(1)
        _invariants(kv)

    def test_boundary_only_cow_and_mid_prefix_raises(self):
        """cow_for_write detaches ONLY the last shared block; a write
        addressed inside the prefix is a corruption bug and raises."""
        kv = _kv()
        assert kv.admit(0, 16, 16, token_ids=P16)
        kv.commit_prefix(0, P16, 16)
        kv.release(0)
        assert kv.admit(1, 16, 16, token_ids=P16)
        kv.take_cow(1)                               # 3 aliased remain
        with pytest.raises(RuntimeError, match="INSIDE"):
            kv.cow_for_write(1, 0)
        src, dst = kv.cow_for_write(1, 11)           # boundary block 2
        assert kv.block_tables[1, 2] == dst != src
        assert kv.cow_for_write(1, 11) is None       # now private
        _invariants(kv)
        kv.release(1)

    def test_commit_dedupe_remaps_to_cached_block(self):
        """Two writers prefilling the same prompt concurrently (the
        second admitted BEFORE the first committed, so no match):
        the later commit dedupes against the tree, frees its private
        duplicate and aliases the cached block."""
        kv = _kv()
        assert kv.admit(0, 8, 8, token_ids=P8)
        assert kv.admit(1, 8, 8, token_ids=P8)       # nothing cached yet
        assert kv.matched_tokens(1) == 0
        kv.commit_prefix(0, P8, 8)
        free_before = kv.stats()["blocks_free"]
        assert kv.commit_prefix(1, P8, 8) == 2
        # both private blocks returned; slot 1 now aliases slot 0's
        assert kv.stats()["blocks_free"] == free_before + 2
        assert kv._owned[1] == []
        assert list(kv.block_tables[1, :2]) == \
            list(kv.block_tables[0, :2])
        for b in kv._shared[1]:
            assert kv._by_block[b].ref == 2
        _invariants(kv)
        kv.release(0)
        kv.release(1)
        _invariants(kv)

    def test_eviction_under_pressure_recovers_admissions(self):
        """Cached (ref-0) prefix blocks are reclaimable supply: an
        admission that outgrows the free list LRU-evicts leaves
        instead of deferring, and the eviction counter moves."""
        kv = _kv(num_blocks=4)
        assert kv.admit(0, 16, 16, token_ids=P16)
        kv.commit_prefix(0, P16, 16)
        kv.release(0)
        st = kv.stats()
        assert st["blocks_free"] == 0
        assert st["blocks_evictable"] == 4
        assert st["blocks_available"] == 4
        # a DIFFERENT prompt: no match, needs 2 real blocks
        assert kv.admit(1, 8, 8, token_ids=[70 + i for i in range(8)])
        assert kv.evictions == 2
        # deepest (leaf) nodes went first; the root-side survive
        assert kv.stats()["blocks_cached"] == 2
        _invariants(kv)
        # and the survivors still match a shorter shared prefix
        kv.release(1)
        assert kv.admit(2, 8, 8, token_ids=P8)
        assert kv.matched_tokens(2) == 7             # full 2-block match
        kv.release(2)
        _invariants(kv)

    def test_matched_path_never_self_evicts(self):
        """Admission increfs its matched path BEFORE allocating, so
        the eviction pass can never reclaim the very blocks the
        admission is aliasing."""
        kv = _kv(num_blocks=5)
        assert kv.admit(0, 16, 16, token_ids=P16)
        kv.commit_prefix(0, P16, 16)
        kv.release(0)
        # full match + COW clone: the pop must evict a TREE leaf (the
        # boundary src it just decref'd is the LRU-newest, so the old
        # spare free block covers it), never blocks 0-2 of the path
        assert kv.admit(1, 16, 16, token_ids=P16)
        path_blocks = list(kv._shared[1])
        assert all(b in kv._by_block for b in path_blocks)
        _invariants(kv)
        kv.release(1)

    def test_prefix_cap_bounds_tree(self):
        """FLAGS_serving_prefix_cache_blocks caps resident tree
        blocks; past the cap, commits evict ref-0 nodes or leave the
        suffix private."""
        kv = _kv(num_blocks=16, prefix_cache_blocks=2)
        assert kv.admit(0, 16, 16, token_ids=P16)
        kv.commit_prefix(0, P16, 16)
        assert kv.stats()["blocks_cached"] == 2      # capped
        _invariants(kv)
        kv.release(0)
        _invariants(kv)

    def test_reset_prefix_cache_requires_drained_slots(self):
        kv = _kv()
        assert kv.admit(0, 8, 8, token_ids=P8)
        kv.commit_prefix(0, P8, 8)
        with pytest.raises(RuntimeError, match="live shared"):
            kv.reset_prefix_cache()
        kv.release(0)
        assert kv.reset_prefix_cache() == 2
        st = kv.stats()
        assert st["blocks_cached"] == 0
        assert st["blocks_free"] == kv.num_blocks
        _invariants(kv)


# ---------------------------------------------------------------------------
# FLAGS_serving_prefix_cache=0: the off path is the old allocator
# ---------------------------------------------------------------------------

class TestPrefixCacheFlagOff:
    def _script(self, kv):
        """A representative allocator scenario (the
        test_serving_paged.py churn slice) returning every observable
        the old design exposed."""
        trace = []
        assert kv.admit(0, 8, 16, token_ids=P8)
        kv.commit_prefix(0, P8, 8)
        assert kv.admit(1, 8, 16, token_ids=P8)      # would match if on
        trace.append(kv.matched_tokens(1))
        kv.ensure_token(0, 8)
        kv.truncate(0, 6)
        kv.release(0)
        assert kv.admit(2, 4, 12, token_ids=P8[:4])
        trace.append((kv.block_tables.copy().tobytes(),
                      tuple(sorted(kv._free)), kv.stats()))
        kv.release(1)
        kv.release(2)
        trace.append(kv.stats())
        return trace

    def test_flag_off_is_byte_identical_to_plain_allocator(self):
        """With the flag off the allocator must behave byte-for-byte
        like one with no prefix machinery at all: same block tables,
        same free list, same stats, zero cache/hit activity — pinned
        by running the same scripted scenario through the flag path
        and the explicit prefix_cache=False constructor."""
        prev = paddle.get_flags(["FLAGS_serving_prefix_cache"])
        paddle.set_flags({"FLAGS_serving_prefix_cache": 0})
        try:
            via_flag = self._script(_kv(num_blocks=8))
        finally:
            paddle.set_flags(prev)
        via_arg = self._script(_kv(num_blocks=8, prefix_cache=False))
        assert via_flag == via_arg
        # no match was served, nothing was cached
        assert via_flag[0] == 0
        final = via_flag[-1]
        assert final["blocks_cached"] == 0
        assert final["blocks_evictable"] == 0
        assert final["prefix_hits"] == 0
        assert final["prefix_tokens_reused"] == 0
        assert final["blocks_used"] == 0
        assert final["blocks_free"] == 8
        # off path: available degenerates to the pre-sharing formula
        st = via_flag[1][2]
        assert st["blocks_available"] == \
            st["blocks_free"] - st["blocks_reserved"]

    @pytest.mark.slow  # ~6s: compiles two engines (flag on AND off)
    def test_flag_off_streams_match_flag_on(self, model, dense_ref):
        """Engine-level pin BOTH ways: repeated shared-prefix prompts
        produce identical greedy streams with the prefix cache on and
        off, and both equal the dense oracle."""
        prev = paddle.get_flags(["FLAGS_serving_prefix_cache"])
        paddle.set_flags({"FLAGS_serving_prefix_cache": 0})
        try:
            off = PagedLlamaDecodeEngine(model, max_slots=2,
                                         max_seq=64, block_size=8,
                                         prefill_chunk=8)
            assert not off._kv.prefix_enabled
            prompts = [list(range(3, 19)), list(range(3, 19)),
                       list(range(3, 19)) + [40, 41]]
            got_off = [off.generate(p, max_new_tokens=8)
                       for p in prompts]
        finally:
            paddle.set_flags(prev)
        assert off._kv.stats()["prefix_hits"] == 0
        on = PagedLlamaDecodeEngine(model, max_slots=2, max_seq=64,
                                    block_size=8, prefill_chunk=8)
        got_on = [on.generate(p, max_new_tokens=8) for p in prompts]
        assert on._kv.stats()["prefix_hits"] >= 1
        for p, a, b in zip(prompts, got_off, got_on):
            want = dense_ref(p, 8)
            assert a == want and b == want, (p, a, b, want)


# ---------------------------------------------------------------------------
# engine: shared prefixes are invisible in the tokens
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def prefix_eng(model):
    """Shared prefix-cache-on engine: 2 slots over 64 tokens, 8-token
    blocks/chunks (so a 16-token prompt is exactly 2 radix nodes)."""
    return PagedLlamaDecodeEngine(model, max_slots=2, max_seq=64,
                                  block_size=8, prefill_chunk=8)


class TestPrefixEngineBitEquality:
    def test_cow_boundary_bit_equal_vs_dense_oracle(
            self, model, dense_ref, prefix_eng):
        """Cold miss, full block-aligned hit (COW boundary clone) and
        partial hit all reproduce the dense stream exactly, while the
        hit/reuse counters prove sharing actually happened."""
        from paddle_tpu.observability import flight

        eng = prefix_eng
        P = list(range(3, 19))                       # 2 full blocks
        st0 = eng._kv.stats()
        cold = eng.generate(P, max_new_tokens=10)
        assert cold == dense_ref(P, 10)
        assert eng._kv.stats()["prefix_hits"] == st0["prefix_hits"]
        # full hit: n-1 tokens skip prefill, boundary block COW-cloned
        hot = eng.generate(P, max_new_tokens=10)
        assert hot == cold
        st1 = eng._kv.stats()
        assert st1["prefix_hits"] == st0["prefix_hits"] + 1
        assert st1["prefix_tokens_reused"] >= \
            st0["prefix_tokens_reused"] + 15
        names = [e["name"] for e in flight.events(category="serving")]
        assert "prefix_hit" in names and "prefix_cow" in names
        # partial hit: shared head, divergent tail
        Q = P[:8] + [50, 51, 52, 53]
        assert eng.generate(Q, max_new_tokens=10) == dense_ref(Q, 10)
        assert eng._kv.stats()["prefix_hits"] == st1["prefix_hits"] + 1
        _invariants(eng._kv)
        assert eng._kv.stats()["blocks_used"] == 0

    def test_interleaved_sharers_and_metrics(self, model, dense_ref,
                                             prefix_eng):
        """Two LIVE slots aliasing one cached prefix decode
        interleaved without cross-talk, and the per-request
        prefix_hit_tokens record survives until release."""
        eng = prefix_eng
        P = list(range(3, 19))
        dense_ref(P, 6)                              # warm the oracle
        eng.generate(P, max_new_tokens=4)            # seed the tree
        o0 = [eng.prefill(0, P, budget=8)]
        o1 = [eng.prefill(1, P, budget=8)]
        assert eng.prefix_hit_tokens[0] == 15
        assert eng.prefix_hit_tokens[1] == 15
        _invariants(eng._kv)
        for _ in range(5):
            nxt = eng.step()
            o0.append(int(nxt[0]))
            o1.append(int(nxt[1]))
        eng.release(0)
        eng.release(1)
        assert 0 not in eng.prefix_hit_tokens
        want = dense_ref(P, 6)
        assert o0 == want and o1 == want
        _invariants(eng._kv)

    @pytest.mark.slow  # ~5s: compiles a fresh engine + draft spec tree
    def test_spec_rollback_over_shared_prefix(self, model, dense_ref):
        """Speculative decode over an aliased prefix: the draft pool
        mirrors the admission (its own radix tree), windows
        accept/roll back across the shared boundary, and the
        committed stream still matches the dense oracle bit-for-bit
        with both pools' invariants intact after every window."""
        eng = PagedLlamaDecodeEngine(model, max_slots=2, max_seq=64,
                                     block_size=8, prefill_chunk=8)
        eng.attach_draft(eng.make_draft(model, num_layers=1),
                         spec_tokens=3)
        P = list(range(3, 19))
        want = dense_ref(P, 12)
        assert eng.generate(P, max_new_tokens=12) == want  # cold
        out = [eng.prefill(0, P, budget=16)]         # hot: prefix hit
        assert eng.prefix_hit_tokens[0] == 15
        assert eng._draft.prefix_hit_tokens[0] == 15
        while len(out) < 12:
            toks, counts = eng.spec_step()
            out.extend(int(t) for t in toks[0, :int(counts[0])])
            _invariants(eng._kv)
            _invariants(eng._draft._kv)
        eng.release(0)
        assert out[:12] == want, (out, want)
        assert eng._kv.stats()["blocks_used"] == 0
        assert eng._draft._kv.stats()["blocks_used"] == 0
        _invariants(eng._kv)
        _invariants(eng._draft._kv)

    def test_reset_state_chaos_mid_prefill(self, model, dense_ref,
                                           prefix_eng):
        """Crash recovery with a warm tree, a live sharer AND a
        mid-prefill staged request: reset_state drops the radix cache
        with the pools (cached content is no longer backed by real
        K/V), and post-reset streams rebuild it from zero, bit-equal.
        This is the supervisor's _handle_death seam — it calls
        exactly this method on the quarantined engine."""
        eng = prefix_eng
        P = list(range(3, 19))
        eng.generate(P, max_new_tokens=4)            # warm tree
        assert eng._kv.stats()["blocks_cached"] > 0
        assert eng.begin_request(0, P, 8)            # live sharer
        assert eng.begin_request(1, list(range(30, 46)), 8)
        eng.prefill_chunk(1)                         # mid-prefill
        eng.reset_state()
        st = eng._kv.stats()
        assert st["blocks_used"] == 0
        assert st["blocks_cached"] == 0
        assert st["blocks_reserved"] == 0
        assert st["blocks_free"] == eng._kv.num_blocks
        assert eng.prefix_hit_tokens == {}
        assert not eng._prefill_state
        _invariants(eng._kv)
        # the tree is gone: the next request is a cold miss that
        # re-seeds it, and the stream is still exact
        st0 = eng._kv.stats()["prefix_hits"]
        assert eng.generate(P, max_new_tokens=6) == dense_ref(P, 6)
        assert eng._kv.stats()["prefix_hits"] == st0
        assert eng.generate(P, max_new_tokens=6) == dense_ref(P, 6)
        assert eng._kv.stats()["prefix_hits"] == st0 + 1
        _invariants(eng._kv)
