"""Vision transforms/ops + audio package + hub/onnx surface tests
(ref: python/paddle/vision/transforms/, vision/ops.py, audio/,
hub.py, onnx/)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle


class TestTransformsFunctional:
    def _img(self, rng):
        return (rng.random((8, 10, 3)) * 255).astype(np.uint8)

    def test_flips_resize_pad_crop(self, rng):
        import paddle_tpu.vision.transforms as T
        img = self._img(rng)
        np.testing.assert_array_equal(T.vflip(T.vflip(img)), img)
        np.testing.assert_array_equal(T.hflip(T.hflip(img)), img)
        assert T.resize(img, (16, 20)).shape == (16, 20, 3)
        np.testing.assert_allclose(
            T.resize(img.astype(np.float32), (8, 10)),
            img.astype(np.float32), atol=1e-3)
        assert T.pad(img, 2).shape == (12, 14, 3)
        assert T.crop(img, 1, 2, 4, 5).shape == (4, 5, 3)
        assert T.center_crop(img, 4).shape == (4, 4, 3)

    def test_geometric_warps_identity(self, rng):
        import paddle_tpu.vision.transforms as T
        img = self._img(rng).astype(np.float32)
        np.testing.assert_allclose(T.rotate(img, 0.0), img, atol=1e-3)
        np.testing.assert_allclose(
            T.affine(img, 0, (0, 0), 1.0, (0, 0)), img, atol=1e-3)
        h, w = img.shape[:2]
        corners = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        np.testing.assert_allclose(
            T.perspective(img, corners, corners), img, atol=1e-2)

    def test_photometric_identities(self, rng):
        import paddle_tpu.vision.transforms as T
        img = self._img(rng)
        np.testing.assert_array_equal(T.adjust_brightness(img, 1.0), img)
        np.testing.assert_array_equal(T.adjust_contrast(img, 1.0), img)
        np.testing.assert_array_equal(T.adjust_saturation(img, 1.0), img)
        # hue: zero shift ~= identity, full cycle ~= identity
        assert np.abs(T.adjust_hue(img, 0.0).astype(int)
                      - img.astype(int)).max() <= 1
        cyc = T.adjust_hue(T.adjust_hue(img, 0.5), 0.5)
        assert np.abs(cyc.astype(int) - img.astype(int)).max() <= 2
        g = T.to_grayscale(img)
        assert g.shape == (8, 10, 1)

    def test_erase_and_random_classes(self, rng):
        import random as pyrandom

        import paddle_tpu.vision.transforms as T
        pyrandom.seed(0)
        img = self._img(rng)
        er = T.erase(img, 1, 1, 3, 3, 0)
        assert er[1:4, 1:4].sum() == 0 and img[1:4, 1:4].sum() > 0
        assert T.RandomResizedCrop(6)(img).shape == (6, 6, 3)
        assert T.ColorJitter(0.2, 0.2, 0.2, 0.1)(img).shape == img.shape
        assert T.RandomAffine(10, (0.1, 0.1), (0.9, 1.1), 5)(
            img).shape == img.shape
        assert T.RandomRotation(15)(img).shape == img.shape
        assert T.RandomPerspective(1.0, 0.3)(img).shape == img.shape
        assert T.Grayscale(3)(img).shape == (8, 10, 3)
        assert T.RandomErasing(1.0)(img).shape == img.shape


class TestDetectionOps:
    def test_yolo_box_and_loss(self, rng):
        import paddle_tpu.vision.ops as V
        S, C = 3, 4
        anchors = [10, 13, 16, 30, 33, 23]
        x = paddle.to_tensor(
            rng.normal(size=(2, S * (5 + C), 4, 4)).astype(np.float32))
        img = paddle.to_tensor(np.array([[128, 128]] * 2, np.int32))
        boxes, scores = V.yolo_box(x, img, anchors, C, 0.5, 32)
        assert boxes.shape == [2, 48, 4] and scores.shape == [2, 48, C]
        gtb = paddle.to_tensor(
            (rng.random((2, 3, 4)) * 64 + 16).astype(np.float32))
        gtl = paddle.to_tensor(rng.integers(0, C, (2, 3)).astype(np.int32))
        xt = paddle.to_tensor(
            rng.normal(size=(2, S * (5 + C), 4, 4)).astype(np.float32)
            * 0.1, stop_gradient=False)
        loss = V.yolo_loss(xt, gtb, gtl, anchors, [0, 1, 2], C, 0.7, 32)
        assert loss.shape == [2] and np.isfinite(loss.numpy()).all()
        loss.sum().backward()
        assert np.isfinite(xt.grad.numpy()).all()

    def test_deform_conv_zero_offsets_is_conv(self, rng):
        import jax
        import jax.numpy as jnp

        import paddle_tpu.vision.ops as V
        xa = rng.normal(size=(1, 2, 6, 6)).astype(np.float32)
        wt = rng.normal(size=(3, 2, 3, 3)).astype(np.float32)
        off = np.zeros((1, 18, 4, 4), np.float32)
        out = V.deform_conv2d(paddle.to_tensor(xa),
                              paddle.to_tensor(off),
                              paddle.to_tensor(wt))
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(xa), jnp.asarray(wt), (1, 1), "VALID")
        np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                                   atol=1e-4)
        layer = V.DeformConv2D(2, 3, 3)
        assert layer(paddle.to_tensor(xa),
                     paddle.to_tensor(off)).shape == [1, 3, 4, 4]

    def test_roi_pool_family(self):
        import paddle_tpu.vision.ops as V
        feat = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        rois = np.array([[0, 0, 4, 4]], np.float32)
        rp = V.roi_pool(paddle.to_tensor(feat), paddle.to_tensor(rois),
                        None, 2)
        np.testing.assert_allclose(rp.numpy()[0, 0],
                                   [[5, 7], [13, 15]])
        featp = np.stack([np.full((4, 4), i, np.float32)
                          for i in range(4)])[None]
        pp = V.psroi_pool(paddle.to_tensor(featp),
                          paddle.to_tensor(rois), None, 2)
        np.testing.assert_allclose(pp.numpy()[0, 0], [[0, 1], [2, 3]])
        ra = V.RoIAlign(2)(paddle.to_tensor(feat),
                           paddle.to_tensor(rois), None)
        assert ra.shape == [1, 1, 2, 2]

    def test_prior_box_fpn_proposals_matrix_nms(self, rng):
        import paddle_tpu.vision.ops as V
        feat = paddle.to_tensor(np.zeros((1, 8, 4, 4), np.float32))
        imgT = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
        pb, pv = V.prior_box(feat, imgT, [16.0], [32.0], [2.0],
                             flip=True)
        assert pb.shape[:2] == [4, 4] and pb.shape[3] == 4
        rois4 = np.array([[0, 0, 32, 32], [0, 0, 200, 200],
                          [0, 0, 64, 64]], np.float32)
        outs, restore, nums = V.distribute_fpn_proposals(
            paddle.to_tensor(rois4), 2, 5, 4, 224)
        assert sum(int(n.numpy()[0]) for n in nums) == 3
        assert sorted(restore.numpy().reshape(-1).tolist()) == [0, 1, 2]
        bb = np.array([[[0, 0, 10, 10], [0, 0, 10, 10],
                        [20, 20, 30, 30]]], np.float32)
        ss = np.array([[[0, 0, 0], [0.9, 0.85, 0.8]]], np.float32)
        out, idx, nn_ = V.matrix_nms(paddle.to_tensor(bb),
                                     paddle.to_tensor(ss), 0.1, 0.05,
                                     10, 10, return_index=True)
        assert out.shape[1] == 6 and int(nn_.numpy()[0]) >= 2
        # decay semantics (ref matrix_nms_kernel.cc iou_max over j<i):
        # b1 duplicates b0 (iou 1 -> decayed to 0, dropped); b2 has no
        # overlap and comp of the top box is 0, so decay==1 exactly.
        kept = sorted(out.numpy()[:, 1].tolist(), reverse=True)
        np.testing.assert_allclose(kept, [0.9, 0.8], atol=1e-6)

    def test_generate_proposals_and_jpeg_io(self, rng, tmp_path):
        import paddle_tpu.vision.ops as V
        an = np.tile(np.array([[0, 0, 16, 16], [8, 8, 24, 24]],
                              np.float32), (9, 1))
        sc = rng.random((1, 2, 3, 3)).astype(np.float32)
        bd = (rng.random((1, 8, 3, 3)).astype(np.float32) - 0.5)
        var = np.tile(np.ones((2, 4), np.float32), (9, 1))
        r, s2, n2 = V.generate_proposals(
            paddle.to_tensor(sc), paddle.to_tensor(bd),
            paddle.to_tensor(np.array([[64, 64]], np.float32)),
            paddle.to_tensor(an), paddle.to_tensor(var))
        assert r.shape[1] == 4 and int(n2.numpy()[0]) == r.shape[0]
        # scores align with the kept ROIs: NMS keep order is descending
        # by score, and every returned score is from the score map
        sv = s2.numpy()
        assert sv.shape[0] == r.shape[0]
        assert np.all(np.diff(sv) <= 1e-7)
        assert np.isin(np.round(sv, 5),
                       np.round(sc.reshape(-1), 5)).all()
        from PIL import Image
        arr = (rng.random((8, 8, 3)) * 255).astype(np.uint8)
        p = str(tmp_path / "t.jpg")
        Image.fromarray(arr).save(p, quality=95)
        dec = V.decode_jpeg(V.read_file(p), mode="rgb")
        assert dec.shape == [3, 8, 8]


class TestAudioPackage:
    def test_functional_tail(self):
        import paddle_tpu.audio as A
        f = A.functional.fft_frequencies(16000, 512)
        assert f.shape == [257]
        assert abs(float(f.numpy()[-1]) - 8000) < 1e-3
        mf = A.functional.mel_frequencies(10, 0, 8000)
        assert np.all(np.diff(mf.numpy()) > 0)
        db = A.functional.power_to_db(
            paddle.to_tensor(np.array([1.0, 0.1], np.float32)))
        np.testing.assert_allclose(db.numpy(), [0.0, -10.0], atol=1e-4)
        w = A.functional.get_window("hamming", 16)
        assert w.shape == [16]

    def test_wav_io_roundtrip(self, tmp_path):
        import paddle_tpu.audio as A
        wav = np.sin(np.linspace(0, 20, 1600)).astype(np.float32)[None]
        p = str(tmp_path / "t.wav")
        A.save(p, paddle.to_tensor(wav), 16000)
        meta = A.info(p)
        assert meta.sample_rate == 16000 and meta.num_channels == 1
        back, sr = A.load(p)
        assert sr == 16000
        np.testing.assert_allclose(back.numpy(), wav, atol=1e-3)
        assert A.backends.get_current_backend() == "wave_backend"
        with pytest.raises(NotImplementedError):
            A.backends.set_backend("soundfile")

    def test_datasets(self):
        import paddle_tpu.audio as A
        ds = A.datasets.ESC50(mode="train")
        wv, lbl = ds[0]
        assert wv.shape == (16000,) and 0 <= lbl < 50
        assert len(A.datasets.TESS()) == 70


class TestFolderDatasetsHubOnnx:
    def test_folder_datasets(self, tmp_path, rng):
        from PIL import Image

        from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder
        for cls in ("cat", "dog"):
            os.makedirs(str(tmp_path / cls))
            Image.fromarray(
                (rng.random((6, 6, 3)) * 255).astype(np.uint8)).save(
                str(tmp_path / cls / "a.png"))
        df = DatasetFolder(str(tmp_path))
        assert len(df) == 2 and df.classes == ["cat", "dog"]
        _, target = df[0]
        assert target == 0
        assert len(ImageFolder(str(tmp_path))) == 2

    def test_hub_local_and_offline_gate(self, tmp_path):
        import paddle_tpu.hub as hub
        (tmp_path / "hubconf.py").write_text(
            "def tiny(n=3):\n    'a tiny model'\n"
            "    return list(range(n))\n")
        d = str(tmp_path)
        assert "tiny" in hub.list(d, source="local")
        assert "tiny model" in hub.help(d, "tiny", source="local")
        assert hub.load(d, "tiny", source="local", n=2) == [0, 1]
        with pytest.raises(RuntimeError, match="offline"):
            hub.load("user/repo", "m")

    def test_onnx_export_gate(self):
        import paddle_tpu.onnx as onnx
        with pytest.raises(ImportError, match="save_inference_model"):
            onnx.export(None, "x")
