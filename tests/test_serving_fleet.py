"""Fleet serving fabric (ISSUE 17): multi-replica router with
failover, process-level chaos recovery, and warm replica resurrection.

Contract pinned here: SIGKILL (or socket death) of one replica of N
mid-stream leaves every accepted request BIT-equal to the
uninterrupted oracle (failover re-dispatches prompt + committed
tokens) with exactly ONE terminal fleet flight event; late responses
from the fenced zombie epoch are discarded, never folded into a
failed-over stream; KV-pressure-aware placement sends no traffic to a
block-starved replica while round-robin (the pinned A/B baseline)
defers there; a request active at ``quarantine_after`` consecutive
replica deaths is failed as poison instead of crash-looping the
fleet; the fleet sheds (FleetSaturated + retry_after) only when EVERY
live replica reports admission pressure level 3; and a dead replica
resurrects from the shared executable cache + warm bundle with 0
fresh XLA compiles.

Cost discipline: router logic runs against jax-free fake replicas
(the PR 15 causal fakes behind REAL sockets speaking the REAL fleet
RPC), so the fast tests compile nothing; the real-subprocess chaos
acceptance (SIGKILL a child pid mid-decode via an armed
``fleet.apply.r<idx>`` site, warm resurrection with cache misses
pinned at 0) is slow-marked.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from paddle_tpu.observability import flight
from paddle_tpu.serving import GenerationServer
from paddle_tpu.serving_fleet import (FleetRouter, FleetSaturated,
                                      ReplicaClient, ReplicaHandle,
                                      ReplicaServer, health_snapshot,
                                      launch_replica)
from paddle_tpu.utils import fault_injection as fi

from test_serving_supervisor import CFG, FakeCausalEngine, FakePagedEngine

FLEET_TERMINAL = {"finished", "failed", "shed"}


def _oracle(prompt, n_new):
    """The uninterrupted greedy stream of the causal fakes — a pure
    recomputation, independent of every server under test."""
    seq = [int(t) for t in prompt]
    out = []
    for _ in range(n_new):
        tok = FakeCausalEngine._next(seq)
        seq.append(tok)
        out.append(tok)
    return out


class StubLevelPolicy:
    """Admission policy double with a hand-set pressure level: admits
    everything replica-side so placement/shed decisions under test are
    exactly the ROUTER's."""

    name = "stub"

    def __init__(self, level=0):
        self.level = level

    def admit_verdict(self, server, prompt_len, max_new, deadline):
        return None

    def on_step(self, server):
        return None


def _mk_replica(idx, engine, policy=None, **handle_kwargs):
    srv = GenerationServer(engine, policy=policy)
    rs = ReplicaServer(srv)
    h = ReplicaHandle(idx, rs.host, rs.port, kill_cb=rs.kill,
                      **handle_kwargs)
    return srv, rs, h


def _teardown(router, replica_servers):
    if router is not None:
        router._stop.set()
    for rs in replica_servers:
        try:
            rs.close(drain=False, timeout=5)
        except Exception:  # noqa: BLE001 — teardown must not mask
            rs.kill()


def _fleet_terminal_counts(trace_ids):
    evs = flight.events(category="fleet")
    return {tid: sum(1 for e in evs
                     if e.get("trace_id") == tid
                     and e["name"] in FLEET_TERMINAL)
            for tid in trace_ids}


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    fi.clear()


# ---------------------------------------------------------------------------
# failover + fencing (jax-free fakes behind real sockets)
# ---------------------------------------------------------------------------

class TestFailover:
    def test_kill_one_of_n_mid_stream_bit_equal(self):
        """The chaos acceptance shape, in-proc: one of 3 replicas dies
        abruptly mid-stream; every request finishes BIT-equal to the
        oracle with exactly one terminal fleet event, and the dead
        replica resurrects via its spawn factory."""
        flight.clear()
        made = []

        def spawn(idx):
            eng = FakeCausalEngine(slots=4, max_seq=64, step_sleep=0.01)
            srv = GenerationServer(eng)
            rs = ReplicaServer(srv)
            made.append(rs)
            return ReplicaHandle(idx, rs.host, rs.port, kill_cb=rs.kill)

        servers, replicas, handles = [], [], []
        for i in range(3):
            eng = FakeCausalEngine(slots=4, max_seq=64, step_sleep=0.01)
            srv, rs, h = _mk_replica(i, eng, spawn=spawn)
            servers.append(srv)
            replicas.append(rs)
            handles.append(h)
        router = FleetRouter(handles, policy="rr",
                             heartbeat_seconds=0.05, heartbeat_misses=2,
                             quarantine_after=3, restart_backoff=0.01,
                             restart_backoff_cap=0.05, max_restarts=5)
        try:
            prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
            reqs = [router.submit(p, 24) for p in prompts]
            time.sleep(0.06)
            assert not all(r["done"].is_set() for r in reqs), \
                "streams finished before the kill — nothing to fail over"
            with router._lock:
                owners = {r["owner"][0] for r in router._inflight.values()
                          if r["owner"]}
            victim = next(h for h in handles if h.idx in owners)
            victim.kill_cb()  # abrupt socket death: the in-proc SIGKILL

            for req, prompt in zip(reqs, prompts):
                assert req["done"].wait(30)
                assert req["error"] is None
                assert req["out"] == _oracle(prompt, 24)
            assert router.failovers >= 1
            counts = _fleet_terminal_counts([r["trace_id"] for r in reqs])
            assert all(c == 1 for c in counts.values()), counts
            names = {e["name"] for e in flight.events(category="fleet")}
            assert {"replica_dead", "failover", "dispatch"} <= names

            deadline = time.monotonic() + 10
            while time.monotonic() < deadline \
                    and router.stats()["live"] < 3:
                time.sleep(0.02)
            assert router.stats()["live"] == 3, \
                "dead replica was not resurrected"
            assert victim.restarts >= 1
            # the rebuilt replica takes traffic like any other
            assert router.generate([9, 9, 7], 6) == _oracle([9, 9, 7], 6)
        finally:
            _teardown(router, replicas + made)

    def test_zombie_epoch_late_response_discarded(self):
        """A fenced replica's late poll responses are dropped by the
        epoch stamp — the failed-over stream stays bit-equal and the
        drop is journaled, never silently folded in."""
        flight.clear()
        servers, replicas, handles = [], [], []
        for i in range(2):
            eng = FakeCausalEngine(slots=2, max_seq=64, step_sleep=0.02)
            srv, rs, h = _mk_replica(i, eng)
            servers.append(srv)
            replicas.append(rs)
            handles.append(h)
        router = FleetRouter(handles, heartbeat_seconds=5.0,
                             quarantine_after=5)
        try:
            req = router.submit([4, 2], 40)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and (
                    req["owner"] is None or len(req["out"]) < 2):
                time.sleep(0.005)
            stale_owner = req["owner"]
            assert stale_owner is not None
            zombie = handles[stale_owner[0]]

            router._replica_down(zombie, reason="test_fence")
            assert req["owner"][0] != zombie.idx, "failover did not move"
            # the zombie is still decoding; simulate its late response
            # arriving after the fence
            router._apply(req, stale_owner, zombie, [123456], False, None)
            assert router.stale_drops >= 1
            assert 123456 not in req["out"]

            assert req["done"].wait(30)
            assert req["error"] is None
            assert req["out"] == _oracle([4, 2], 40)
            evs = flight.events(category="fleet")
            assert any(e["name"] == "stale_drop"
                       and e.get("trace_id") == req["trace_id"]
                       for e in evs)
            assert _fleet_terminal_counts(
                [req["trace_id"]])[req["trace_id"]] == 1
        finally:
            _teardown(router, replicas)

    def test_poison_quarantined_after_two_replica_deaths(self):
        """A request active at quarantine_after consecutive replica
        deaths is failed as poison — one terminal event, counted, and
        never re-dispatched a third time."""
        flight.clear()
        servers, replicas, handles = [], [], []
        for i in range(2):
            eng = FakeCausalEngine(slots=2, max_seq=80, step_sleep=0.02)
            srv, rs, h = _mk_replica(i, eng)
            servers.append(srv)
            replicas.append(rs)
            handles.append(h)
        router = FleetRouter(handles, heartbeat_seconds=5.0,
                             quarantine_after=2)
        try:
            req = router.submit([7, 7, 7], 60)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and req["owner"] is None:
                time.sleep(0.005)
            first = handles[req["owner"][0]]
            router._replica_down(first, reason="death_one")
            assert not req["done"].is_set()
            assert req["strikes"] == 1
            second = handles[req["owner"][0]]
            assert second.idx != first.idx
            router._replica_down(second, reason="death_two")

            assert req["done"].wait(5)
            assert isinstance(req["error"], RuntimeError)
            assert "poison" in str(req["error"])
            assert router.quarantined == 1
            evs = flight.events(category="fleet")
            assert any(e["name"] == "quarantined"
                       and e.get("trace_id") == req["trace_id"]
                       for e in evs)
            assert _fleet_terminal_counts(
                [req["trace_id"]])[req["trace_id"]] == 1
        finally:
            _teardown(router, replicas)


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------

class TestPlacement:
    def _run(self, policy):
        """One fleet with replica 0 KV-starved by a hog request that
        holds its ENTIRE block pool; returns (admitted_without_deferral,
        starved_dispatched) for 6 short requests."""
        servers, replicas, handles = [], [], []
        for i in range(3):
            eng = FakePagedEngine(slots=2, max_seq=64, block_size=8,
                                  num_blocks=(6 if i == 0 else 32),
                                  step_sleep=0.01)
            srv, rs, h = _mk_replica(i, eng, policy=StubLevelPolicy(0))
            servers.append(srv)
            replicas.append(rs)
            handles.append(h)
        router = FleetRouter(handles, policy=policy,
                             heartbeat_seconds=5.0)
        try:
            # the hog goes through replica 0's OWN admission path:
            # prompt 8 + budget 40 = 48 tokens = all 6 blocks, held for
            # 40 slow steps — anything placed there must defer
            hog = servers[0].submit([3] * 8, 40)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and \
                    servers[0].engine._kv.available_blocks() > 0:
                time.sleep(0.005)
            assert servers[0].engine._kv.available_blocks() == 0

            for h in handles:
                h.health = h.probe_health(1.0)
            assert handles[0].health["blocks_free"] == 0
            assert handles[1].health["blocks_free"] == 32

            prompts = [[i + 1, i + 2, 5] for i in range(6)]
            reqs = [router.submit(p, 4) for p in prompts]
            for req, prompt in zip(reqs, prompts):
                assert req["done"].wait(30)
                assert req["error"] is None
                assert req["out"] == _oracle(prompt, 4)
            assert hog["done"].wait(30)
            # every request the router placed on the starved replica
            # sat in its deferred-admission queue behind the hog
            deferred = handles[0].dispatched
            return 6 - deferred, deferred
        finally:
            _teardown(router, replicas)

    def test_pressure_placement_beats_round_robin(self):
        """The evidence-driven pin: under a KV-starved replica, the
        pressure policy admits strictly MORE requests without deferral
        than round-robin, and sends the starved replica nothing."""
        pressure_score, pressure_deferred = self._run("pressure")
        rr_score, rr_deferred = self._run("rr")
        assert pressure_deferred == 0, \
            "pressure policy placed traffic on the starved replica"
        assert rr_deferred >= 1, \
            "round-robin avoided the starved replica — no contrast"
        assert pressure_score > rr_score


# ---------------------------------------------------------------------------
# fleet-level shed
# ---------------------------------------------------------------------------

class TestFleetShed:
    def test_shed_only_when_every_replica_at_level3(self):
        flight.clear()
        servers, replicas, handles = [], [], []
        for i in range(3):
            eng = FakeCausalEngine(slots=2, max_seq=64)
            srv, rs, h = _mk_replica(i, eng, policy=StubLevelPolicy(3))
            servers.append(srv)
            replicas.append(rs)
            handles.append(h)
        router = FleetRouter(handles, heartbeat_seconds=5.0,
                             retry_after=0.25)
        try:
            for h in handles:
                h.health = h.probe_health(1.0)
            with pytest.raises(FleetSaturated) as exc:
                router.submit([1, 2], 4)
            assert exc.value.retry_after == 0.25
            assert router.shed == 1
            evs = flight.events(category="fleet")
            assert any(e["name"] == "fleet_shed"
                       and e["attrs"].get("retry_after") == 0.25
                       for e in evs)

            # ONE replica dropping below hard shed reopens the fleet —
            # and placement goes exactly there
            servers[1].policy.level = 0
            handles[1].health = handles[1].probe_health(1.0)
            assert router.generate([2, 4, 6], 5) == _oracle([2, 4, 6], 5)
            assert handles[1].dispatched == 1
            assert handles[0].dispatched == handles[2].dispatched == 0
        finally:
            _teardown(router, replicas)


# ---------------------------------------------------------------------------
# /healthz — one source of truth with the router probe
# ---------------------------------------------------------------------------

class TestHealthz:
    def test_snapshot_shapes(self):
        srv = GenerationServer(FakeCausalEngine(slots=2, max_seq=64))
        try:
            snap = health_snapshot(srv)
            assert snap["ok"] and snap["loop_alive"]
            assert snap["blocks_total"] == -1  # dense: no pool gauge
            paged = GenerationServer(
                FakePagedEngine(slots=2, max_seq=64, num_blocks=8))
            try:
                psnap = health_snapshot(paged)
                assert psnap["blocks_total"] == 8
                assert psnap["blocks_free"] == 8
            finally:
                paged.shutdown(drain=False, timeout=5)
        finally:
            srv.shutdown(drain=False, timeout=5)

    def test_healthz_endpoint_reports_readiness(self):
        ok_srv = GenerationServer(FakeCausalEngine(slots=2, max_seq=64))
        bad_srv = GenerationServer(FakeCausalEngine(slots=2, max_seq=64),
                                   policy=StubLevelPolicy(3))
        try:
            ep = ok_srv.metrics_endpoint(port=0)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{ep.port}/healthz",
                    timeout=5) as resp:
                body = json.loads(resp.read())
            assert resp.status == 200
            assert body["ok"] and body["level"] == 0

            ep2 = bad_srv.metrics_endpoint(port=0)
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{ep2.port}/healthz", timeout=5)
            assert exc.value.code == 503
            body = json.loads(exc.value.read())
            assert not body["ok"] and body["level"] == 3
        finally:
            ok_srv.shutdown(drain=False, timeout=5)
            bad_srv.shutdown(drain=False, timeout=5)


# ---------------------------------------------------------------------------
# transport chaos primitives (satellite: fault_injection growth)
# ---------------------------------------------------------------------------

class _ScriptedConn:
    def __init__(self, frames=()):
        self.sent = []
        self.frames = list(frames)
        self.closed = False

    def send(self, obj):
        self.sent.append(obj)

    def recv(self):
        return self.frames.pop(0)

    def close(self):
        self.closed = True


class TestFlakyTransport:
    def test_send_duplicate_and_drop(self):
        conn = _ScriptedConn()
        ft = fi.FlakyTransport(conn, "tx.a")
        fi.inject_transport("tx.a.send", duplicate=True, times=1)
        ft.send({"x": 1})
        ft.send({"x": 2})
        assert conn.sent == [{"x": 1}, {"x": 1}, {"x": 2}]

        conn2 = _ScriptedConn()
        ft2 = fi.FlakyTransport(conn2, "tx.b")
        fi.inject_transport("tx.b.send", drop=True, times=1)
        ft2.send({"x": 1})  # vanishes
        ft2.send({"x": 2})
        assert conn2.sent == [{"x": 2}]

    def test_recv_drop_duplicate_delay_and_passthrough(self):
        ft = fi.FlakyTransport(_ScriptedConn([1, 2, 3]), "tx.c")
        fi.inject_transport("tx.c.recv", drop=True, times=1)
        assert ft.recv() == 2  # frame 1 discarded, next delivered
        assert ft.recv() == 3

        ft2 = fi.FlakyTransport(_ScriptedConn([7, 8]), "tx.d")
        fi.inject_transport("tx.d.recv", duplicate=True, times=1)
        assert ft2.recv() == 7
        assert ft2.recv() == 7  # the replayed duplicate
        assert ft2.recv() == 8

        conn = _ScriptedConn([5])
        ft3 = fi.FlakyTransport(conn, "tx.e")
        fi.inject_transport("tx.e.recv", delay=0.05, times=1)
        t0 = time.monotonic()
        assert ft3.recv() == 5
        assert time.monotonic() - t0 >= 0.05
        ft3.close()  # __getattr__ passthrough
        assert conn.closed

    def test_skip_counts_clean_frames_first(self):
        conn = _ScriptedConn()
        ft = fi.FlakyTransport(conn, "tx.f")
        fi.inject_transport("tx.f.send", drop=True, times=1, skip=2)
        for i in range(4):
            ft.send(i)
        assert conn.sent == [0, 1, 3]  # exactly the 3rd frame vanished

    def test_kill_pid_is_armed_site_gated(self):
        assert fi.kill_pid("fleet.kill.unarmed", os.getpid()) is False
        # refuses the calling process even when armed
        fi.inject("fleet.kill.self")
        assert fi.kill_pid("fleet.kill.self", os.getpid()) is False
        child = subprocess.Popen([sys.executable, "-c",
                                  "import time; time.sleep(60)"])
        try:
            fi.inject("fleet.kill.child", times=1)
            assert fi.kill_pid("fleet.kill.child", child.pid) is True
            assert child.wait(timeout=10) == -signal.SIGKILL
            # the shot was consumed: the site is disarmed again
            assert fi.kill_pid("fleet.kill.child", child.pid) is False
        finally:
            if child.poll() is None:
                child.kill()


# ---------------------------------------------------------------------------
# real-subprocess chaos acceptance (slow: boots child processes and
# compiles the tiny model once to seed the shared executable cache)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestSubprocessFleet:
    def test_sigkill_chaos_bit_equal_and_warm_resurrection(self, tmp_path):
        """The ISSUE acceptance scenario end to end: 3 real replica
        processes warm-booted from one bundle; an armed
        ``fleet.apply.r1`` site SIGKILLs replica 1 mid-decode; every
        accepted request finishes bit-equal to the single-server
        oracle with one terminal fleet event; the replacement replica
        rejoins from the warm bundle with cache misses still 0."""
        cache = tmp_path / "xcache"
        bundle = tmp_path / "warm.npz"
        env = {"FLAGS_executable_cache_dir": str(cache)}
        base = {"model": {"kind": "tiny_llama", "seed": 7, "config": CFG},
                "max_slots": 2, "max_seq": 64, "block_size": 8,
                "prefill_chunk": 8, "supervised": True}

        # ONE cold boot compiles everything, then seals the bundle —
        # and doubles as the uninterrupted single-server oracle
        cold = dict(base, prime=[1, 2, 3, 4], prime_tokens=4,
                    export_bundle=str(bundle))
        proc, port, boot = launch_replica(cold, env=env)
        prompts = [[1, 2, 3], [2, 3, 4], [3, 4, 5], [4, 5, 6], [9, 9]]
        oracle = {}
        try:
            cli = ReplicaClient("127.0.0.1", port)
            for p in prompts:
                oracle[tuple(p)] = cli.generate(p, 16, timeout=120)
            # rollout duck-type over RPC: retain + identity swap
            token = cli.engine.params
            res = cli.swap_weights(prepared=token)
            assert res["seconds"] >= 0
            cli._call({"op": "shutdown", "drain": True})
            cli.close()
        finally:
            proc.wait(timeout=60)
        assert boot["cache"]["misses"] > 0  # the cold boot compiled

        from paddle_tpu.serving_fleet import spawn_fleet
        flight.clear()
        warm = dict(base, warm_bundle=str(bundle))
        router = spawn_fleet(
            3, warm, env=env,
            router_kwargs=dict(policy="rr", heartbeat_seconds=0.2,
                               heartbeat_misses=2, restart_backoff=0.05,
                               max_restarts=4))
        try:
            for h in router.replicas:
                stats = h.call({"op": "cache_stats"})["cache"]
                assert stats["misses"] == 0, \
                    f"replica {h.idx} warm boot compiled fresh: {stats}"

            # SIGKILL replica 1 the moment the router applies its 4th
            # streamed token batch — deterministically mid-decode
            fi.inject("fleet.apply.r1", times=1, skip=3)
            reqs = [router.submit(p, 16) for p in prompts[:4]]
            for req, p in zip(reqs, prompts[:4]):
                assert req["done"].wait(120)
                assert req["error"] is None
                assert req["out"] == oracle[tuple(p)]
            assert router.failovers >= 1
            assert any(e["name"] == "replica_dead"
                       and e["attrs"].get("replica") == 1
                       for e in flight.events(category="fleet"))
            counts = _fleet_terminal_counts([r["trace_id"] for r in reqs])
            assert all(c == 1 for c in counts.values()), counts

            deadline = time.monotonic() + 120
            while time.monotonic() < deadline \
                    and router.stats()["live"] < 3:
                time.sleep(0.1)
            assert router.stats()["live"] == 3, \
                "SIGKILLed replica did not resurrect"
            reborn = router.replicas[1]
            assert reborn.restarts >= 1
            stats = reborn.call({"op": "cache_stats"})["cache"]
            assert stats["misses"] == 0, \
                f"resurrection compiled fresh XLA programs: {stats}"
            # the reborn replica serves bit-equal traffic
            assert router.generate([9, 9], 16, timeout=120) \
                == oracle[(9, 9)]
        finally:
            fi.clear()
            router.shutdown(drain=False, timeout=30)
