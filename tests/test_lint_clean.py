"""The source linter AND the static capture pass run clean inside
tier-1.

Same pattern as test_flags_docs.py: the rule set + allowlist are pinned
together, so a new violation (an unguarded registry sweep, a stray
.numpy() on a hot path, a bare except, a fusable marker without its
impl, an unallowlisted graph break in a step function) fails tests
instead of landing silently. Deliberate exceptions go in
paddle_tpu/analysis/allowlist.py WITH a justification — never by
weakening a rule.
"""
import paddle_tpu  # noqa: F401 — ops.yaml + fusion registries loaded
from paddle_tpu.analysis.lint import lint


def test_repo_lints_clean():
    r = lint()
    assert not r.parse_errors, r.parse_errors
    assert not r.diagnostics, (
        "lint violations introduced:\n"
        + "\n".join(d.render() for d in r.diagnostics)
        + "\n\nfix the site, or add a justified entry to "
          "paddle_tpu/analysis/allowlist.py")


def test_lint_scans_the_whole_package():
    r = lint()
    assert r.files_scanned > 150  # the package, not a subset


def test_suppressions_are_justified():
    from paddle_tpu.analysis.allowlist import (ALLOWLIST,
                                               CAPTURE_ALLOWLIST)
    for rule, pattern, why in ALLOWLIST + CAPTURE_ALLOWLIST:
        assert rule and pattern, (rule, pattern)
        assert len(why.split()) >= 4, (
            f"allowlist entry ({rule}, {pattern!r}) needs a real "
            f"justification, got {why!r}")


def test_repo_step_functions_capture_clean():
    """The static capture pass over the package's own step functions
    (hapi train/eval batch, serving decode step, the bench step): a new
    unallowlisted PTC diagnostic — a fresh graph break landing in a
    step path — fails CI here, exactly like a lint violation."""
    from paddle_tpu.analysis.capture import scan_repo_steps
    r = scan_repo_steps()
    assert not r.diagnostics, (
        "capture-plan violations introduced in step functions:\n"
        + "\n".join(d.render() for d in r.diagnostics)
        + "\n\nfix the break (hoist the read, move the side effect to "
          "the step boundary), or add a justified CAPTURE_ALLOWLIST "
          "entry in paddle_tpu/analysis/allowlist.py")
    assert len(r.functions) >= 5  # the step inventory actually scanned
