"""The source linter runs clean over paddle_tpu/ inside tier-1.

Same pattern as test_flags_docs.py: the rule set + allowlist are pinned
together, so a new violation (an unguarded registry sweep, a stray
.numpy() on a hot path, a bare except, a fusable marker without its
impl) fails tests instead of landing silently. Deliberate exceptions go
in paddle_tpu/analysis/allowlist.py WITH a justification — never by
weakening a rule.
"""
import paddle_tpu  # noqa: F401 — ops.yaml + fusion registries loaded
from paddle_tpu.analysis.lint import lint


def test_repo_lints_clean():
    r = lint()
    assert not r.parse_errors, r.parse_errors
    assert not r.diagnostics, (
        "lint violations introduced:\n"
        + "\n".join(d.render() for d in r.diagnostics)
        + "\n\nfix the site, or add a justified entry to "
          "paddle_tpu/analysis/allowlist.py")


def test_lint_scans_the_whole_package():
    r = lint()
    assert r.files_scanned > 150  # the package, not a subset


def test_suppressions_are_justified():
    from paddle_tpu.analysis.allowlist import ALLOWLIST
    for rule, pattern, why in ALLOWLIST:
        assert rule and pattern, (rule, pattern)
        assert len(why.split()) >= 4, (
            f"allowlist entry ({rule}, {pattern!r}) needs a real "
            f"justification, got {why!r}")
