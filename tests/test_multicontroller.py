"""Multi-controller (regime-2) compiled collectives: the production
transport — jax.distributed.initialize spanning processes, collectives
riding the interconnect inside compiled programs.

Matches the reference's real-transport distributed tests, which shell
out actual worker processes and run NCCL rings
(ref: test/collective/test_communication_api_base.py:28,58-79,
process_group_nccl.cc:732). Here: 2 processes on the CPU backend with
gloo cross-process collectives, wired through the launch CLI.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(tmp_path, script_body, nproc=2, env=None):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(script_body))
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--log_dir", str(tmp_path / "log"),
           "--nproc_per_node", str(nproc), str(script)]
    e = dict(os.environ, PYTHONPATH=_REPO_ROOT, JAX_PLATFORMS="cpu")
    # the conftest's 8-virtual-device XLA_FLAGS must NOT leak into the
    # workers: each controller owns exactly its own devices
    e.pop("XLA_FLAGS", None)
    if env:
        e.update(env)
    return subprocess.run(cmd, capture_output=True, text=True, timeout=240,
                          env=e, cwd=_REPO_ROOT), tmp_path / "log"


MC_PRELUDE = """
    import os
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    import jax
    # the whole point: a REAL multi-controller runtime, not the
    # host-staged store fallback
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2
    assert len(jax.local_devices()) == 1
    r, n = dist.get_rank(), dist.get_world_size()
"""


class TestMultiController:
    def test_compiled_psum_allgather_spans_processes(self, tmp_path):
        proc, log = _run_launch(tmp_path, MC_PRELUDE + """
    # compiled all_reduce (psum over the 2-process gloo ring)
    t = paddle.to_tensor(np.full((4,), float(r + 1), np.float32))
    dist.all_reduce(t)
    np.testing.assert_allclose(t.numpy(), np.full((4,), 3.0))

    # compiled all_gather
    outs = []
    dist.all_gather(outs, paddle.to_tensor(
        np.full((2,), float(10 * (r + 1)), np.float32)))
    assert len(outs) == 2
    np.testing.assert_allclose(outs[0].numpy(), [10.0, 10.0])
    np.testing.assert_allclose(outs[1].numpy(), [20.0, 20.0])

    # max / avg reductions
    t2 = paddle.to_tensor(np.full((3,), float(r), np.float32))
    dist.all_reduce(t2, dist.ReduceOp.MAX)
    np.testing.assert_allclose(t2.numpy(), np.full((3,), 1.0))

    # reduce_scatter through the compiled path
    parts = [paddle.to_tensor(np.full((2,), float(r + 1 + i), np.float32))
             for i in range(n)]
    out = paddle.to_tensor(np.zeros((2,), np.float32))
    dist.reduce_scatter(out, parts)
    # rank k gets sum_r (r+1+k) = (1+k) + (2+k)
    np.testing.assert_allclose(out.numpy(), np.full((2,), 3.0 + 2 * r))

    # barrier rides the same compiled ring
    dist.barrier()
    print("MC_COLLECTIVES_OK", r)
        """)
        assert proc.returncode == 0, proc.stderr + proc.stdout
        for i in range(2):
            body = (log / f"workerlog.{i}").read_text()
            assert f"MC_COLLECTIVES_OK {i}" in body, body

    def test_dp_train_step_spans_processes(self, tmp_path):
        """One DP train step over a mesh spanning both processes; loss
        and updated params must match the single-controller oracle (the
        reference's acc-align contract, test/collective/fleet)."""
        proc, log = _run_launch(tmp_path, MC_PRELUDE + """
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    rep = NamedSharding(mesh, P())
    dsh = NamedSharding(mesh, P("dp", None))

    rng = np.random.default_rng(0)
    W0 = rng.normal(size=(8, 4)).astype(np.float32)
    Xg = rng.normal(size=(4, 8)).astype(np.float32)   # global batch
    Yg = rng.normal(size=(4, 4)).astype(np.float32)

    # each process feeds ITS batch shard (rows r*2:(r+1)*2)
    Xl = Xg[r * 2:(r + 1) * 2]
    Yl = Yg[r * 2:(r + 1) * 2]
    X = jax.make_array_from_single_device_arrays(
        Xg.shape, dsh, [jax.device_put(Xl, jax.local_devices()[0])])
    Y = jax.make_array_from_single_device_arrays(
        Yg.shape, dsh, [jax.device_put(Yl, jax.local_devices()[0])])
    W = jax.device_put(jnp.asarray(W0), rep)

    @jax.jit
    def step(W, X, Y):
        def loss_fn(W):
            return jnp.mean((X @ W - Y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(W)
        return loss, W - 0.1 * g

    loss, W1 = step(W, X, Y)
    loss = float(jax.device_get(loss))

    # single-controller oracle computed locally (pure numpy)
    def np_step(W, X, Y):
        pred = X @ W
        loss = ((pred - Y) ** 2).mean()
        g = 2 * X.T @ (pred - Y) / pred.size
        return loss, W - 0.1 * g

    eloss, eW1 = np_step(W0, Xg, Yg)
    assert abs(loss - eloss) < 1e-5, (loss, eloss)
    W1h = np.asarray(jax.device_get(W1))
    np.testing.assert_allclose(W1h, eW1, rtol=1e-5, atol=1e-6)
    print("MC_DP_STEP_OK", r, round(loss, 6))
        """)
        assert proc.returncode == 0, proc.stderr + proc.stdout
        for i in range(2):
            body = (log / f"workerlog.{i}").read_text()
            assert f"MC_DP_STEP_OK {i}" in body, body
