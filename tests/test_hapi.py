"""hapi Model.fit/evaluate/predict + callbacks + summary/flops tests
(ref: python/paddle/hapi/model.py, callbacks.py, dynamic_flops.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Model, flops, summary
from paddle_tpu.hapi.callbacks import (Callback, EarlyStopping,
                                       ModelCheckpoint, VisualDL)
from paddle_tpu.metric import Accuracy


def _toy_data(n=64, din=4, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, din)).astype(np.float32)
    W = rng.normal(size=(din, classes)).astype(np.float32)
    y = (X @ W).argmax(-1).astype(np.int64)
    return X, y


def _model():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 32), nn.ReLU(), nn.Linear(32, 3))
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.Adam(
        learning_rate=0.01, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(), metrics=Accuracy())
    return m


def test_fit_decreases_loss_and_returns_history():
    X, y = _toy_data()
    m = _model()
    hist = m.fit((X, y), batch_size=16, epochs=8, verbose=0)
    assert "loss" in hist
    assert hist["loss"][-1] < hist["loss"][0] * 0.5


def test_fit_with_eval_and_accuracy():
    X, y = _toy_data()
    m = _model()
    hist = m.fit((X, y), eval_data=(X, y), batch_size=16, epochs=6,
                 verbose=0)
    assert "eval_acc" in hist
    assert hist["eval_acc"][-1] > 0.8


def test_evaluate_and_predict():
    X, y = _toy_data()
    m = _model()
    m.fit((X, y), batch_size=16, epochs=6, verbose=0)
    logs = m.evaluate((X, y), batch_size=16, verbose=0)
    assert logs["acc"] > 0.8 and "loss" in logs
    preds = m.predict((X, y), batch_size=16, stack_outputs=True)
    assert preds[0].shape == (64, 3)


def test_save_load_roundtrip(tmp_path):
    X, y = _toy_data()
    m = _model()
    m.fit((X, y), batch_size=16, epochs=2, verbose=0)
    path = str(tmp_path / "ckpt" / "model")
    m.save(path)
    m2 = _model()
    m2.load(path)
    np.testing.assert_allclose(
        m2.network[0].weight.numpy(), m.network[0].weight.numpy())


def test_model_checkpoint_callback(tmp_path):
    import os
    X, y = _toy_data()
    m = _model()
    m.fit((X, y), batch_size=32, epochs=2, verbose=0,
          callbacks=[ModelCheckpoint(save_freq=1,
                                     save_dir=str(tmp_path))])
    assert os.path.exists(str(tmp_path / "0.pdparams"))
    assert os.path.exists(str(tmp_path / "final.pdparams"))


def test_early_stopping_stops():
    X, y = _toy_data()
    m = _model()
    es = EarlyStopping(monitor="loss", patience=1, verbose=0,
                       min_delta=10.0)  # impossible improvement bar
    hist = m.fit((X, y), eval_data=(X, y), batch_size=16, epochs=20,
                 verbose=0, callbacks=[es])
    assert len(hist["loss"]) < 20, "early stopping never fired"


def test_custom_callback_hooks_fire():
    X, y = _toy_data()
    seen = []

    class Probe(Callback):
        def on_epoch_begin(self, epoch, logs=None):
            seen.append(("begin", epoch))

        def on_epoch_end(self, epoch, logs=None):
            seen.append(("end", epoch, sorted((logs or {}).keys())))

    m = _model()
    m.fit((X, y), batch_size=32, epochs=2, verbose=0,
          callbacks=[Probe()])
    assert ("begin", 0) in seen and ("begin", 1) in seen
    assert any(e[0] == "end" and "loss" in e[2] for e in seen)


def test_visualdl_writes_scalars(tmp_path):
    import json
    X, y = _toy_data()
    m = _model()
    m.fit((X, y), batch_size=32, epochs=1, verbose=0,
          callbacks=[VisualDL(log_dir=str(tmp_path))])
    lines = (tmp_path / "scalars.jsonl").read_text().splitlines()
    recs = [json.loads(l) for l in lines]
    assert any(r["tag"] == "train/loss" for r in recs)


def test_summary_counts_params(capsys):
    net = nn.Sequential(nn.Linear(4, 32), nn.ReLU(), nn.Linear(32, 3))
    info = summary(net, (1, 4))
    want = 4 * 32 + 32 + 32 * 3 + 3
    assert info["total_params"] == want
    assert info["trainable_params"] == want
    out = capsys.readouterr().out
    assert "Linear" in out and str(want) in out


def test_flops_linear_and_conv():
    net = nn.Linear(4, 8)
    n = flops(net, (2, 4))
    assert n == 2 * 8 * 4  # out elems * in features
    conv = nn.Conv2D(3, 16, 3, padding=1)
    n2 = flops(conv, (1, 3, 8, 8))
    assert n2 == 16 * 8 * 8 * 3 * 9  # out elems * (I/g * k*k)


def test_dataset_input_path():
    from paddle_tpu.io import TensorDataset
    X, y = _toy_data(n=32)
    ds = TensorDataset([paddle.to_tensor(X), paddle.to_tensor(y)])
    m = _model()
    hist = m.fit(ds, batch_size=8, epochs=2, verbose=0, shuffle=False)
    assert len(hist["loss"]) == 2
