"""Dataset zoo tests (ref: python/paddle/vision/datasets/,
python/paddle/text/datasets/ — served synthetically, zero egress)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import DataLoader
from paddle_tpu.text.datasets import (Conll05, Imdb, Imikolov, Movielens,
                                      UCIHousing, WMT14, WMT16)
from paddle_tpu.vision.datasets import Flowers, VOC2012


def test_vision_dataset_shapes():
    f = Flowers(mode="train")
    img, lbl = f[0]
    assert img.shape[-1] == 3 and 0 <= int(lbl) < 102
    v = VOC2012(mode="test")
    img, mask = v[0]
    assert img.shape == (3, 64, 64) and mask.shape == (64, 64)
    assert mask.max() < 21


def test_text_dataset_structures():
    d = Imdb(mode="train")
    doc, lbl = d[5]
    assert doc.dtype == np.int64 and int(lbl) in (0, 1)

    ng = Imikolov(data_type="NGRAM", window_size=5)
    assert len(ng[0]) == 5

    ml = Movielens()
    sample = ml[0]
    assert len(sample) == 6 and isinstance(sample[5], np.float32)

    c = Conll05()
    s = c[0]
    assert len(s) == 9
    assert all(len(x) == len(s[0]) for x in s)

    for cls in (WMT14, WMT16):
        src, trg, nxt = cls()[0]
        assert trg[0] == cls.BOS and nxt[-1] == cls.EOS
        np.testing.assert_array_equal(trg[1:], nxt[:-1])


def test_datasets_deterministic():
    a, b = Imdb(mode="train"), Imdb(mode="train")
    np.testing.assert_array_equal(a[3][0], b[3][0])
    t = Imdb(mode="test")
    assert len(t) < len(a)


def test_uci_housing_end_to_end_regression():
    """The synthetic UCIHousing target is linear+noise: a linear model
    must fit it well through the hapi loop."""
    from paddle_tpu.hapi import Model

    train = UCIHousing(mode="train")
    net = nn.Linear(13, 1)
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.Adam(
        learning_rate=0.1, parameters=net.parameters()),
        loss=nn.MSELoss())
    hist = m.fit(train, batch_size=64, epochs=40, verbose=0)
    # target mean is 22.5, so initial MSE ~ 500; the linear fit must get
    # well under the constant-predictor floor
    assert hist["loss"][-1] < hist["loss"][0] * 0.05


def test_dataloader_over_voc():
    dl = DataLoader(VOC2012(mode="test"), batch_size=8)
    imgs, masks = next(iter(dl))
    assert tuple(imgs.shape) == (8, 3, 64, 64)
    assert tuple(masks.shape) == (8, 64, 64)
