"""Tied-weight pipeline realization (VERDICT r4 #7): the compiled
Engine pipeline path handles SharedLayerDesc-style models — a tied
embedding/lm-head whose single Parameter is used by both the first and
last stage — with the gradient merge the reference does via a shared-
param allreduce across owning stages
(ref: fleet/meta_parallel/parallel_layers/pp_layers.py:92,257)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.auto_parallel.engine_pp import (
    PipelineTrainStep, build_pipeline_model, detect_pipeline_split)
from paddle_tpu.distributed.fleet.pp_layers import (LayerDesc,
                                                    SharedLayerDesc)

V, H, B, T = 32, 16, 16, 4


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(H, H)

    def forward(self, x):
        return x + F.gelu(self.fc(x))


class TiedHead(nn.Layer):
    """Projects through the embedding's OWN weight (the tie)."""

    def __init__(self, emb):
        super().__init__()
        self.emb = emb

    def forward(self, x):
        return paddle.matmul(x, paddle.transpose(self.emb.weight, [1, 0]))


def _make_tied_model():
    paddle.seed(0)
    emb = nn.Embedding(V, H)
    return nn.Sequential(emb, *[Block() for _ in range(4)],
                         TiedHead(emb))


def _loss_fn(logits, labels):
    return F.cross_entropy(
        logits.reshape([-1, V]), labels.reshape([-1])).mean()


def _oracle_losses(model_factory, ids, labels, steps):
    m = model_factory()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    out = []
    for _ in range(steps):
        loss = _loss_fn(m(paddle.to_tensor(ids)),
                        paddle.to_tensor(labels))
        loss.backward()
        opt.step()
        opt.clear_grad()
        out.append(float(loss))
    return out, m


class TestTiedPipeline:
    def test_detect_split_sees_tied_ends(self):
        m = _make_tied_model()
        pre, fam, post = detect_pipeline_split(m)
        assert len(pre) == 1 and len(fam) == 4 and len(post) == 1

    def test_tied_weights_train_like_serial(self):
        """pp=4 compiled pipeline on a tied-embedding LM == the serial
        oracle, loss for loss — the tied weight receives BOTH stages'
        gradients exactly once."""
        rng = np.random.default_rng(0)
        ids = rng.integers(0, V, (B, T)).astype(np.int32)
        labels = rng.integers(0, V, (B, T)).astype(np.int64)

        expected, m_ref = _oracle_losses(_make_tied_model, ids, labels, 3)

        m = _make_tied_model()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        step = PipelineTrainStep(m, _loss_fn, opt, pp=4, micro_batches=4)
        assert "shared" in step._params, "tied weight not detected"
        got = [float(step(ids, labels)) for _ in range(3)]
        np.testing.assert_allclose(got, expected, rtol=2e-4)

        # the embedding weight object stays THE tie and matches serial
        emb_w = m[0].weight
        assert m[5].emb.weight is emb_w
        np.testing.assert_allclose(np.asarray(emb_w._data),
                                   np.asarray(m_ref[0].weight._data),
                                   rtol=1e-4, atol=1e-5)

    def test_untied_model_has_no_shared_section(self):
        paddle.seed(0)
        m = nn.Sequential(nn.Embedding(V, H),
                          *[Block() for _ in range(4)],
                          nn.Linear(H, V))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        step = PipelineTrainStep(m, _loss_fn, opt, pp=4, micro_batches=4)
        assert "shared" not in step._params

    def test_build_from_layer_descs(self):
        """fleet's LayerDesc/SharedLayerDesc list realizes into the
        compiled pipeline: same-key SharedLayerDescs share ONE layer
        instance and the step ties them."""
        def head_fwd(emb_layer, x):
            return paddle.matmul(x, paddle.transpose(emb_layer.weight, [1, 0]))

        paddle.seed(0)
        descs = [SharedLayerDesc("emb", nn.Embedding, None, "weight",
                                 V, H)] \
            + [LayerDesc(Block) for _ in range(4)] \
            + [SharedLayerDesc("emb", nn.Embedding, head_fwd, "weight",
                               V, H)]
        m = build_pipeline_model(descs)
        # one instance: both use-sites expose the same Tensor
        assert m[0].inner.weight is m[5].inner.weight

        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        step = PipelineTrainStep(m, _loss_fn, opt, pp=4, micro_batches=4)
        assert "shared" in step._params

        rng = np.random.default_rng(1)
        ids = rng.integers(0, V, (B, T)).astype(np.int32)
        labels = rng.integers(0, V, (B, T)).astype(np.int64)

        def rebuild():
            paddle.seed(0)
            return build_pipeline_model(
                [SharedLayerDesc("emb", nn.Embedding, None, "weight",
                                 V, H)]
                + [LayerDesc(Block) for _ in range(4)]
                + [SharedLayerDesc("emb", nn.Embedding, head_fwd,
                                   "weight", V, H)])

        expected, _ = _oracle_losses(rebuild, ids, labels, 2)
        got = [float(step(ids, labels)) for _ in range(2)]
        np.testing.assert_allclose(got, expected, rtol=2e-4)


def test_named_parameters_dedups_tied_across_modules():
    """A Parameter reachable via two submodules yields ONCE from the
    whole-model walk (torch/reference semantics) — a per-level memo
    made eager optimizers double-update tied weights (found by the
    tied-pipeline oracle comparison above)."""
    m = _make_tied_model()
    ps = m.parameters()
    assert len(ps) == len({id(p) for p in ps})
    # the tie is still reachable through both paths
    assert m[0].weight is m[5].emb.weight
